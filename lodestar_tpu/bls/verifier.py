"""TPU-backed BLS verifier service — the reference's north-star seam.

Reference analog: `IBlsVerifier` + `BlsMultiThreadWorkerPool`
(chain/bls/interface.ts:25-68, chain/bls/multithread/index.ts:113,
SURVEY.md §2.3). The pool's contract is kept exactly:

  - `verify_signature_sets(sets, batchable, priority)` — batchable sets
    are buffered up to MAX_BUFFER_WAIT_MS / MAX_BUFFERED_SIGS and merged
    with other callers' work (index.ts:59-74, 320-339); a failed batch
    is re-verified job-by-job then set-by-set so one bad signature only
    fails its own caller (interface.ts:4-12, worker.ts:88-103).
  - `verify_signature_sets_same_message(sets, message)` — random-
    weighted aggregation + one pairing check; on failure, per-signature
    retry fan-out (jobItem.ts:96-125, index.ts:552-563).
  - `can_accept_work()` — backpressure for the gossip processor
    (index.ts:149-155, network/processor/index.ts).

What changes vs the reference's worker pool (index.ts:183-199,
519-534): instead of ≤128-set chunks round-robined to N-1 CPU threads,
each drain of the queue becomes a WAVE — every queued job's sets packed
into device buckets of up to DEVICE_BUCKET_MAX (per-op device cost is
batch-flat to ~2048, so big buckets are nearly free), all buckets
dispatched asynchronously, and ONE stacked verdict readback per wave
(a fresh readback through the TPU tunnel costs ~100 ms; dispatches
~0.1 ms). Host-side set preparation (decompression, hash-to-G2 — C
calls that release the GIL) runs on a thread pool and overlaps the
device's execution of the previous wave. With more than one device the
bucket batch axis is sharded over a `jax.sharding.Mesh`
(lodestar_tpu/parallel) — the SPMD replacement for the reference's
worker fan-out.

CONTINUOUS BATCHING (the small-bucket gossip path): bulk waves pack
themselves, but the production steady state is trickle traffic —
single aggregates and 32-sig buffer flushes. Those small batchable
jobs accumulate in a ROLLING device bucket shared across waves
(inference-server discipline: coalesce until the batch is worth the
hardware) and flush when the bucket reaches the device-ingest gate,
when non-batchable work dispatches anyway, or when the oldest job's
latency budget (default 50 ms past queue admission, on top of the
100 ms gossip buffer) expires — so trickle coalesces into
device-ingest-sized buckets without unbounded latency. Dispatches are
counted per bucket size and per path (ingest / host / host_cold), and
submit-to-verdict latency feeds p50/p99 histograms on /metrics.

OVERLAPPED WAVE PIPELINE (ISSUE 16): waves are DOUBLE-BUFFERED — each
wave's host prep + dispatch runs as its own task, so while wave N
executes on the device, wave N+1's prep (decompression, padding, limb
packing) runs on the thread pool and its dispatch queues behind N via
JAX async dispatch (donated input buffers on TPU let XLA reuse wave
N's freed device memory). The pipeline depth is a knob
(`pipeline_depth` / LODESTAR_TPU_PIPELINE_DEPTH, default 2; 1 = the
pre-pipeline synchronous behavior), `is_quiescent`/`close()` extend
over the prefetch window so autotune re-tunes and shutdown cannot
race an in-flight prep, and occupancy (fraction of wall time with ≥1
wave in flight) plus prep-overlap-hidden seconds are exported on
/metrics.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import secrets
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import jax.numpy as jnp

from ..crypto.bls import curve as oc

# The latency histogram became an executor-level primitive when the
# node-wide QoS scheduler landed (device/executor.py tracks per-class
# submit-to-completion latency with the same class); re-exported here
# because the verifier's own histograms predate it and tests/tools
# reach it as verifier.LatencyHistogram.
from ..device.executor import LatencyHistogram  # noqa: F401
from ..device.health import classify_device_error, watchdog_deadline_s
from ..metrics import device as _device
from ..ops import curve as C
from . import api, kernels

MAX_BUFFER_WAIT_MS = 100  # index.ts:74
MAX_BUFFERED_SIGS = 32  # index.ts:65
MAX_SIGNATURE_SETS_PER_JOB = 128  # index.ts:56 (job granularity for retries)
DEVICE_BUCKET_MAX = 2048  # sets per device dispatch (batch-flat cost)
QUEUE_MAX_LENGTH = 512  # canAcceptWork threshold, index.ts:149-155
# Continuous batching: small batchable jobs accumulate in a ROLLING
# device bucket shared across waves, flushed when it reaches the
# device-ingest gate or when the oldest job has waited this long past
# its queue admission — ~50 ms on top of the 100 ms gossip buffer.
LATENCY_BUDGET_MS = 50
# Overlapped wave pipeline: how many waves may be in the prep+dispatch
# window at once. Depth d admits wave N+1's host prep while wave N
# still executes on device; 1 restores the synchronous pre-pipeline
# behavior (prep of N+1 starts only after N is dispatched).
PIPELINE_DEPTH = int(os.environ.get("LODESTAR_TPU_PIPELINE_DEPTH", "2"))


def _rand_scalars(n: int):
    """Nonzero 64-bit blinding scalars (blst batch-verify width)."""
    return [secrets.randbits(kernels.RAND_BITS) | 1 for _ in range(n)]


_PAD_CACHE: list = []


def _pad_prepared() -> "_PreparedSet":
    """A valid prepared set used for bucket padding (mask False slots
    still flow through the device sqrt/ladder chains)."""
    if not _PAD_CACHE:
        from ..crypto.bls.signature import sign, sk_to_pk

        msg = b"\x5a" * 32
        sig = sign(7, msg)
        xc0, xc1, s, ok = api.parse_signature(sig)
        assert ok
        _PAD_CACHE.append(
            _PreparedSet(
                api.decompress_pubkey(sk_to_pk(7)),
                (xc0, xc1),
                s,
                api.message_draws(msg),
            )
        )
    return _PAD_CACHE[0]


@dataclass
class _PreparedSet:
    """Host-light prepared set for DEVICE ingestion: the pubkey is
    decompressed on host (cached — validators recur), but signatures
    stay as compressed x-coordinates and messages as hash_to_field
    draws; sqrt/subgroup/SSWU run batched on the TPU
    (kernels.run_verify_batch_ingest_async, composing the
    _stage_g2_* and _stage_sswu_*/_stage_cofactor sub-stages)."""

    pk: tuple  # affine G1 ints (cache-decompressed)
    sig_x: tuple  # (xc0, xc1) compressed signature x
    sig_sign: bool
    draws: tuple  # (u0, u1) Fq2 hash_to_field draws
    sig_raw: bytes = b""  # compressed bytes (small-bucket host path)
    msg_raw: bytes = b""  # signing root (small-bucket host path)


@dataclass
class _Job:
    sets: list  # raw api.SignatureSet list
    future: asyncio.Future
    batchable: bool
    created_at: float = 0.0  # caller submit time (latency histogram)
    enqueued_at: float = 0.0
    prepared: list | None = None
    # dispatch-to-readback time of the wave that carried this job's
    # verdict — grafted under the caller's bls_verify_job span as a
    # backdated device child (metrics/tracing.attach_completed_span)
    device_s: float = 0.0


# -- host-oracle failover verdicts (device/health.py fault domain) -----
# Exact per-set pairing checks — the same math OracleBlsVerifier runs,
# so verdicts are bit-identical to the device path (the differential
# suite proves all three agree). Used while the device is quarantined;
# they deliberately touch NO jax arrays (jnp.asarray on a sick TPU
# could hang the failover itself).


def _host_oracle_sets(sets) -> bool:
    """Verdict for raw api.SignatureSet items (a _Job's .sets)."""
    from ..crypto.bls import pairing as op

    try:
        for s in sets:
            pk = api.decompress_pubkey(s.pubkey)
            h = api.message_to_g2(s.message)
            sig = api.decompress_signature(s.signature)
            if sig is None:
                return False
            if not op.pairing_product_is_one(
                [(pk, h), (oc.g1_neg(oc.G1_GEN), sig)]
            ):
                return False
        return True
    except api.InvalidPointError:
        return False


def _host_oracle_prepared(sets) -> bool:
    """Verdict for _PreparedSet items (a packed bucket): the pubkey is
    already decompressed; signature/message reconstruct from the raw
    bytes (or the parsed x-coordinate / hash draws)."""
    from ..crypto.bls import pairing as op

    try:
        for s in sets:
            sig = (
                api.decompress_signature(s.sig_raw)
                if s.sig_raw
                else api.decompress_signature_parsed(
                    s.sig_x, s.sig_sign
                )
            )
            if sig is None:
                return False
            h = (
                api.message_to_g2(s.msg_raw)
                if s.msg_raw
                else api.draws_to_g2(s.draws)
            )
            if not op.pairing_product_is_one(
                [(s.pk, h), (oc.g1_neg(oc.G1_GEN), sig)]
            ):
                return False
        return True
    except api.InvalidPointError:
        return False


def _host_oracle_same_message(pairs, h) -> bool:
    """Verdict for same-message (pk, sig_x, sig_sign) triples against
    one already-hashed G2 point h."""
    from ..crypto.bls import pairing as op

    try:
        for pk, sx, sg in pairs:
            sig = api.decompress_signature_parsed(sx, sg)
            if sig is None:
                return False
            if not op.pairing_product_is_one(
                [(pk, h), (oc.g1_neg(oc.G1_GEN), sig)]
            ):
                return False
        return True
    except api.InvalidPointError:
        return False


class BlsVerifierMetrics:
    """Counter names mirror lodestar_bls_thread_pool_* so the reference
    Grafana dashboard maps 1:1 (metrics/metrics/lodestar.ts:403-506)."""

    def __init__(self):
        self.job_groups_started = 0
        self.jobs_started = 0
        self.sig_sets_started = 0
        self.batch_retries = 0
        self.batch_sigs_success = 0
        self.same_message_retries = 0
        self.queue_length = 0
        self.total_job_wait_s = 0.0
        self.total_device_time_s = 0.0
        self.waves = 0
        self.buckets_dispatched = 0
        # last-wave stats for the TPU verifier dashboard
        self.last_wave_sets = 0
        self.last_wave_duration_s = 0.0
        self.wave_sets_total = 0
        # overlapped pipeline: host prep seconds that ran while
        # another wave was already in flight — work the pipeline hid
        # behind device execution instead of serializing ahead of it
        self.prep_overlap_hidden_s = 0.0
        # continuous batching: per-bucket-size device dispatches, path
        # split (device ingest vs host decompress/hash vs cold-compile
        # host fallback), rolling-bucket flush triggers, and the
        # submit-to-verdict latency histogram
        self.dispatch_by_bucket: dict[int, int] = {}
        self.dispatch_by_path = {
            "ingest": 0,
            "host": 0,
            "host_cold": 0,
            # device path quarantined (device/health.py): the bucket
            # rode the bit-identical host oracle instead
            "failover": 0,
        }
        # dispatches count from executor threads; scrapes copy under
        # the same lock so iteration never races an insertion
        self.dispatch_lock = threading.Lock()
        self.rolling_flushes = {"full": 0, "deadline": 0, "merged": 0}
        self.rolling_sets = 0  # current rolling-bucket occupancy
        self.host_invalid_jobs = 0
        self.verify_latency = LatencyHistogram()
        self.same_message_latency = LatencyHistogram()

    def snapshot_dispatch(self) -> tuple[dict[int, int], dict[str, int]]:
        """Copies of the dispatch counters taken under the dispatch
        lock, so a scrape never iterates a dict an executor thread is
        inserting into."""
        with self.dispatch_lock:
            return dict(self.dispatch_by_bucket), dict(self.dispatch_by_path)


class TpuBlsVerifier:
    """`IBlsVerifier` over TPU pairing kernels.

    mesh: None = auto (make a Mesh over all local devices when more
    than one is visible); pass an explicit `jax.sharding.Mesh` to pin
    (tests use the 8-device CPU mesh), or `False` to force single-device.
    """

    def __init__(
        self,
        max_buffer_wait_ms: int = MAX_BUFFER_WAIT_MS,
        max_buffered_sigs: int = MAX_BUFFERED_SIGS,
        queue_max: int = QUEUE_MAX_LENGTH,
        mesh=None,
        prep_workers: int | None = None,
        ingest_min_bucket: int | None = None,
        latency_budget_ms: int = LATENCY_BUDGET_MS,
        warmup: bool = False,
        host_fallback_when_cold: bool | None = None,
        pipeline_depth: int | None = None,
    ):
        """Continuous-batching knobs:

        ingest_min_bucket: device-ingest gate override (None = the
          kernels module knob / LODESTAR_TPU_INGEST_MIN_BUCKET).
        latency_budget_ms: how long the rolling gossip bucket may hold
          a batchable job past queue admission before a deadline flush
          (0 disables the rolling bucket — every wave dispatches
          immediately, the pre-round-6 behavior).
        pipeline_depth: overlapped-wave pipeline depth (None = the
          LODESTAR_TPU_PIPELINE_DEPTH env default, 2). Depth d lets
          up to d-1 waves prep/dispatch ahead of the wave executing
          on device; 1 = synchronous pre-pipeline behavior.
        warmup: pre-compile the ingest pipeline for every eligible
          bucket size on a background thread (node start).
        host_fallback_when_cold: route ingest-eligible buckets to the
          host decompress/hash path while their XLA compile is still
          cold (default: enabled iff warmup is — tests and benches
          without warmup keep the deterministic device path).
        """
        self.metrics = BlsVerifierMetrics()
        self._max_wait = max_buffer_wait_ms / 1000.0
        self._max_buffered = max_buffered_sigs
        self._max_sets_per_job = MAX_SIGNATURE_SETS_PER_JOB
        self._queue_max = queue_max
        self._ingest_min = ingest_min_bucket
        self._latency_budget = latency_budget_ms / 1000.0
        # None = unset: follows warmup, and start_warmup() (the node
        # calls it on any verifier it's handed) turns the fallback on
        # so in-flight compiles never stall a live wave. An explicit
        # True/False always wins.
        self._cold_fallback_explicit = host_fallback_when_cold
        self._cold_fallback = (
            warmup
            if host_fallback_when_cold is None
            else host_fallback_when_cold
        )
        self._pipeline_depth = max(
            1,
            int(
                pipeline_depth
                if pipeline_depth is not None
                else PIPELINE_DEPTH
            ),
        )
        self._rolling: list[_Job] = []
        self._rolling_sets = 0
        self._rolling_task: asyncio.Task | None = None
        self._dispatching = 0  # waves between job pop and finalizer
        # overlapped pipeline: in-flight prep+dispatch tasks, and the
        # occupancy clock (cumulative seconds with >=1 wave in flight)
        self._wave_tasks: set[asyncio.Task] = set()
        self._born = time.monotonic()
        self._busy_since: float | None = None
        self._busy_total = 0.0
        self._intake_held = 0  # hold_intake() nesting depth
        self._buffer: list[_Job] = []
        self._buffer_task: asyncio.Task | None = None
        # priority queue: (priority_class, seq) keeps FIFO within class;
        # priority jobs jump the queue (reference jobs.unshift,
        # chain/bls/interface.ts:19-22)
        self._queue: asyncio.PriorityQueue = asyncio.PriorityQueue()
        self._seq = 0
        self._runner: asyncio.Task | None = None
        self._finalizers: set[asyncio.Task] = set()
        self._closed = False
        self._executor = None  # node DeviceExecutor (attach_executor)
        # device fault domain (device/health.py, attach_health):
        # while the tracker quarantines the device every bucket rides
        # the bit-identical host oracle, and — when a wave timeout is
        # armed — a wave stuck on a hung device fails over instead of
        # leaving its verdict futures pending forever
        self._health = None
        self._wave_timeout_s: float | None = None
        self._log = None
        if mesh is None:
            import jax

            devs = jax.devices()
            if len(devs) > 1:
                from .. import parallel

                mesh = parallel.make_mesh()
            else:
                mesh = False
        self._mesh = mesh or None
        # Host prep (decompression + hash-to-G2) is ctypes C that
        # releases the GIL — a pool genuinely parallelizes it across
        # cores and overlaps it with device execution.
        self._prep_pool = ThreadPoolExecutor(
            prep_workers
            if prep_workers is not None
            else min(8, os.cpu_count() or 4),
            thread_name_prefix="bls-prep",
        )
        if warmup:
            self.start_warmup()

    # -- continuous-batching configuration -----------------------------

    def _ingest_gate(self) -> int:
        """The live device-ingest bucket gate."""
        return (
            self._ingest_min
            if self._ingest_min is not None
            else kernels.ingest_min_bucket()
        )

    def _bucket_cap(self) -> int:
        """Sets per device dispatch: the ladder's live top rung,
        bounded by the hard DEVICE_BUCKET_MAX. Reads the ladder every
        wave so a set_ladder_top() retune (device/autotune.py) applies
        to the next packing without a restart."""
        return min(DEVICE_BUCKET_MAX, kernels.ladder_top())

    def set_latency_budget_ms(self, ms: float) -> None:
        """Live retune of the rolling-bucket latency budget (the
        autotuner's fourth knob). Applies to the NEXT deadline arming;
        an already-armed deadline keeps its schedule — the budget is
        an upper bound on added wait, and rescheduling mid-flight
        could extend a promise already made to a queued job."""
        self._latency_budget = max(0.0, float(ms)) / 1000.0

    def latency_budget_ms(self) -> float:
        return self._latency_budget * 1000.0

    def set_pipeline_depth(self, depth: int) -> None:
        """Live retune of the overlapped-pipeline depth (autotune's
        fifth knob). Applies to the NEXT wave admission; waves already
        in the prefetch window keep their slot."""
        self._pipeline_depth = max(1, int(depth))

    def pipeline_depth(self) -> int:
        return self._pipeline_depth

    # -- overlapped-pipeline bookkeeping -------------------------------

    def _inflight(self) -> int:
        """Waves anywhere in the pipeline: prepping/dispatching
        (_wave_tasks) or on device awaiting readback (_finalizers)."""
        return len(self._wave_tasks) + len(self._finalizers)

    def _occupancy_mark(self) -> None:
        """Record a possible busy/idle transition of the pipeline.
        Called whenever _wave_tasks/_finalizers membership changes;
        the event loop is single-threaded, so no lock is needed."""
        now = time.monotonic()
        if self._inflight() > 0:
            if self._busy_since is None:
                self._busy_since = now
        elif self._busy_since is not None:
            self._busy_total += now - self._busy_since
            self._busy_since = None

    def pipeline_occupancy(self) -> float:
        """Fraction of this verifier's wall time with >=1 wave in
        flight (lodestar_jax_pipeline_occupancy). High occupancy with
        depth >= 2 means the overlap is keeping the device fed."""
        now = time.monotonic()
        total = now - self._born
        if total <= 0.0:
            return 0.0
        busy = self._busy_total
        if self._busy_since is not None:
            busy += now - self._busy_since
        return min(1.0, busy / total)

    def is_quiescent(self) -> bool:
        """No queued, buffered, rolling, or in-flight work — the gate
        the drift monitor (device/autotune.py) requires before a
        re-tune may touch live knobs (a backend switch mid-wave would
        drop the very traces the wave is executing). `_dispatching`
        covers the prep-and-dispatch window: jobs are already popped
        from the queue but the finalizer task is not yet registered,
        so none of the other indicators would show the wave.
        `_wave_tasks` covers the overlapped pipeline's PREFETCH window
        (ISSUE 16 quiescence bugfix): a wave whose prep is running as
        a pipeline task is invisible to `_dispatching` once
        _dispatch_wave has returned, and a re-tune that cleared jit
        caches mid-prefetch would recompile — or worse, retune knobs
        — under a wave that already sampled them."""
        return (
            self._dispatching == 0
            and self._queue.empty()
            and not self._buffer
            and not self._rolling
            and not self._wave_tasks
            and not self._finalizers
        )

    def has_pending_deadline_work(self) -> bool:
        """Deadline work WAITING for the device: queued/buffered/
        rolling jobs, or a wave inside the prep-and-dispatch window.
        This is the executor's deadline probe — while True, the
        executor defers bulk/maintenance picks so the next wave
        boundary belongs to gossip verdicts. Deliberately narrower
        than `not is_quiescent()`: a wave already EXECUTING on device
        (`_finalizers`) does not defer bulk — the chip is busy either
        way, and deferring on in-flight waves would starve blob
        batches under any sustained gossip."""
        return bool(
            self._dispatching
            or not self._queue.empty()
            or self._buffer
            or self._rolling
            or self._wave_tasks
        )

    def attach_executor(self, executor) -> None:
        """Join the node-wide DeviceExecutor (device/executor.py) as
        its deadline-class client: register the pending-work and
        quiescence probes, and gate this verifier's intake on the
        executor's (a drain closes can_accept_work here with no
        hold_intake call). The wave pipeline itself stays in this
        class — verdicts are bit-identical and depth semantics are
        unchanged; the executor schedules AROUND it."""
        self._executor = executor
        if executor is not None:
            executor.register_deadline_probe(
                self.has_pending_deadline_work
            )
            executor.register_quiescence_probe(self.is_quiescent)

    def attach_health(self, tracker, wave_timeout_s=None) -> None:
        """Join the device fault domain (device/health.py): while the
        tracker quarantines the device, every bucket short-circuits to
        the bit-identical host oracle (verdicts exact per-set pairing
        checks — the differential tests prove identity), device errors
        report through the taxonomy, and `wave_timeout_s` arms a wave
        watchdog — a wave stuck past the deadline trips the tracker
        and resolves every pending verdict via host failover (zero
        lost, zero wrong). wave_timeout_s=None adopts the
        deadline-class default derived from the fused stage budget;
        pass 0/False to leave the wave watchdog unarmed (CPU
        emulation, where the TPU budget means nothing)."""
        self._health = tracker
        if wave_timeout_s is None:
            self._wave_timeout_s = watchdog_deadline_s("deadline")
        elif wave_timeout_s:
            self._wave_timeout_s = float(wave_timeout_s)
        else:
            self._wave_timeout_s = None

    def _health_log(self):
        if self._log is None:
            from ..logger import get_logger

            self._log = get_logger("bls-verifier")
        return self._log

    def _flush_target(self) -> int:
        """Rolling-bucket full threshold: the smallest device-ingest-
        eligible bucket size."""
        return max(4, self._ingest_gate())

    def _use_ingest(self, b: int, kind: str = "batch") -> bool:
        """Device ingest for a bucket of size b on the given pipeline
        (batch / same_message — distinct jit programs)? Gated by size
        and — when the cold-compile host fallback is on — by whether
        that pipeline's compile for b is already warm."""
        if b < self._ingest_gate():
            return False
        if self._cold_fallback and not kernels.ingest_is_warm(b, kind):
            return False
        return True

    def _count_dispatch(self, b: int, use_ingest: bool, failover: bool = False):
        """Per-bucket-size and per-path dispatch counters (the proof
        that trickle traffic coalesces into device-ingest buckets).
        Runs on executor threads — the lock keeps concurrent
        read-modify-write increments from losing counts (scrapers
        read via snapshot copies, see metrics bridging in node.py)."""
        m = self.metrics
        with m.dispatch_lock:
            m.dispatch_by_bucket[b] = (
                m.dispatch_by_bucket.get(b, 0) + 1
            )
            if failover:
                path = "failover"  # device quarantined: host oracle
            elif use_ingest:
                path = "ingest"
            elif b >= self._ingest_gate():
                path = "host_cold"  # eligible, but compile still cold
            else:
                path = "host"
            m.dispatch_by_path[path] += 1

    def start_warmup(self, block: bool = False):
        """Pre-compile the ingest pipeline for every eligible bucket
        size through the persistent cache (background thread unless
        block). While a size is cold the verifier serves it from the
        host path — starting warmup turns host_fallback_when_cold on
        (unless it was explicitly disabled), otherwise the first live
        ingest bucket would dispatch straight into the very compile
        the warmup thread is paying for."""
        if self._mesh is not None:
            # jit specializes on input shardings: the warmup's
            # unsharded dispatches would compile a DIFFERENT
            # executable than this verifier's mesh-sharded buckets
            # and falsely mark the sizes warm. With no sharded warmup
            # available, cold fallback would route ingest-eligible
            # buckets to the host path FOREVER (nothing else marks
            # them warm), so mesh verifiers keep direct dispatch:
            # first live bucket per size pays the compile inline,
            # once, persistent-cached (the pre-round-6 behavior).
            if self._cold_fallback_explicit is None:
                self._cold_fallback = False
            return None
        if self._cold_fallback_explicit is None:
            self._cold_fallback = True
        return kernels.warmup_ingest(
            kernels.default_warmup_sizes(self._ingest_gate()),
            block=block,
        )

    # -- IBlsVerifier surface ------------------------------------------

    def can_accept_work(self) -> bool:
        # with a node executor attached, its intake state is part of
        # this verifier's: an executor drain (the re-tune window)
        # closes the processor-fed path exactly like hold_intake did
        if self._executor is not None and not self._executor.can_accept_work(
            "deadline"
        ):
            return False
        return (
            not self._closed
            and not self._intake_held
            and self._queue.qsize()
            + len(self._buffer)
            + len(self._rolling)
            < self._queue_max
        )

    @contextlib.contextmanager
    def hold_intake(self):
        """Backpressure gossip intake (can_accept_work -> False) for
        the duration of the block. The drift monitor wraps a re-tune
        in this so the quiescence it checked once keeps holding for
        the processor-fed path; callers that bypass can_accept_work
        (block import) can still submit — a mid-tune wave then pays
        recompile latency, never wrong verdicts (the cleared caches
        re-trace deterministically)."""
        self._intake_held += 1
        try:
            yield
        finally:
            self._intake_held -= 1

    @property
    def in_flight_waves(self) -> int:
        """Waves dispatched to the device and not yet finalized — the
        device dispatch-queue depth (lodestar_jax_dispatch_queue_depth)."""
        return len(self._finalizers)

    async def verify_signature_sets(
        self,
        sets: list[api.SignatureSet],
        batchable: bool = False,
        priority: bool = False,
    ) -> bool:
        """True iff every set verifies. Malformed points -> False
        (maybeBatch.ts:17-44 semantics). Decompression/hashing is
        deferred to the wave's prep stage (thread pool), keeping the
        event loop free.

        When the caller runs inside a block-import trace (the chain's
        sig_verify stage, metrics/tracing.py), this job's submit-to-
        verdict interval lands as a nested span in the trace tree —
        the contextvar copied at task spawn carries the parent."""
        from ..metrics.tracing import attach_completed_span, child_span

        with child_span("bls_verify_job"):
            self._ensure_runner()
            fut = asyncio.get_event_loop().create_future()
            job = _Job(list(sets), fut, batchable, time.monotonic())
            self.metrics.sig_sets_started += len(job.sets)
            if batchable and len(job.sets) < self._max_buffered:
                self._buffer.append(job)
                buffered = sum(len(j.sets) for j in self._buffer)
                if buffered >= self._max_buffered:
                    self._flush_buffer()
                elif self._buffer_task is None:
                    self._buffer_task = asyncio.ensure_future(
                        self._flush_after_wait()
                    )
            else:
                self._enqueue([job], priority)
            ok = await fut
            # device-side child span under this job's span: the wave's
            # dispatch-to-readback interval, learned at finalize
            attach_completed_span("device_wave", job.device_s)
            return ok

    async def verify_signature_sets_same_message(
        self, sets: list[api.SameMessageSet], message: bytes
    ) -> list[bool]:
        """Per-set verdicts for k (pubkey, signature) pairs on one
        message (jobItem.ts:50-92)."""
        t0 = time.monotonic()
        try:
            return await self._verify_same_message_timed(sets, message)
        finally:
            self.metrics.same_message_latency.observe(
                time.monotonic() - t0
            )

    async def _verify_same_message_timed(
        self, sets: list[api.SameMessageSet], message: bytes
    ) -> list[bool]:
        self._ensure_runner()
        loop = asyncio.get_event_loop()

        def prep():
            # ONE host hash for the whole group (amortized by the
            # attData-keyed queue); signatures stay compressed — the
            # device decompresses them
            h = api.message_to_g2(message)
            draws = api.message_draws(message)
            out = []
            for s in sets:
                try:
                    pk = api.decompress_pubkey(s.pubkey)
                except api.InvalidPointError:
                    out.append(None)
                    continue
                xc0, xc1, sign, ok = api.parse_signature(s.signature)
                out.append(
                    ((pk, (xc0, xc1), sign)) if ok else None
                )
            return h, draws, out

        h, draws, prepared = await loop.run_in_executor(
            self._prep_pool, prep
        )
        live = [i for i, p in enumerate(prepared) if p is not None]
        if not live:
            return [False] * len(sets)
        results = [False] * len(sets)
        ok = await self._run_same_message(
            [prepared[i] for i in live], h
        )
        if ok:
            for i in live:
                results[i] = True
            return results
        # batch failed: per-signature retry fan-out (index.ts:552-563)
        self.metrics.same_message_retries += 1
        singles = await self._verdict_wave(
            [
                [
                    _PreparedSet(
                        prepared[i][0],
                        prepared[i][1],
                        prepared[i][2],
                        draws,
                    )
                ]
                for i in live
            ]
        )
        for i, r in zip(live, singles):
            results[i] = r
        return results

    async def close(self):
        """Reject all pending work (the reference rejects queued jobs on
        worker termination, index.ts:311-318) and stop the runner."""
        self._closed = True
        if self._buffer_task:
            self._buffer_task.cancel()
            self._buffer_task = None
        if self._rolling_task:
            self._rolling_task.cancel()
            self._rolling_task = None
        err = RuntimeError("BLS verifier closed")
        for j in self._buffer:
            if not j.future.done():
                j.future.set_exception(err)
        self._buffer = []
        for j in self._rolling:
            if not j.future.done():
                j.future.set_exception(err)
        self._rolling = []
        self._rolling_sets = 0
        self.metrics.rolling_sets = 0
        while not self._queue.empty():
            _, _, jobs = self._queue.get_nowait()
            for j in jobs:
                if not j.future.done():
                    j.future.set_exception(err)
        if self._runner:
            self._runner.cancel()
            self._runner = None
        # cancel the prefetch window first: a wave task cancelled here
        # fails its jobs (see _run_wave / _wave_done), never leaves a
        # caller awaiting a future its wave will no longer resolve
        for t in list(self._wave_tasks):
            t.cancel()
        for t in list(self._finalizers):
            t.cancel()
        self._prep_pool.shutdown(wait=False)

    # -- internals ------------------------------------------------------

    def _prepare(self, s: api.SignatureSet) -> _PreparedSet | None:
        """Host prep: pubkey cache + byte parsing + message expansion.
        None = malformed on host (bad flags / non-canonical / infinity
        signature) -> the job resolves False without device work
        (maybeBatch.ts:17-44 semantics)."""
        pk = api.decompress_pubkey(s.pubkey)
        xc0, xc1, sign, ok = api.parse_signature(s.signature)
        if not ok:
            return None
        draws = api.message_draws(s.message)
        return _PreparedSet(
            pk, (xc0, xc1), sign, draws, s.signature, s.message
        )

    def _ensure_runner(self):
        if self._closed:
            # the reference rejects work after termination (index.ts:311-318)
            raise RuntimeError("BLS verifier closed")
        if self._runner is None or self._runner.done():
            self._runner = asyncio.ensure_future(self._run_loop())

    def _enqueue(self, jobs: list[_Job], priority: bool = False):
        self.metrics.job_groups_started += 1
        now = time.monotonic()
        for j in jobs:
            j.enqueued_at = now
        self._seq += 1
        self._queue.put_nowait((0 if priority else 1, self._seq, jobs))
        self.metrics.queue_length = self._queue.qsize()

    def _flush_buffer(self):
        if self._buffer_task:
            self._buffer_task.cancel()
            self._buffer_task = None
        jobs, self._buffer = self._buffer, []
        if jobs:
            self._enqueue(jobs)

    async def _flush_after_wait(self):
        try:
            await asyncio.sleep(self._max_wait)
        except asyncio.CancelledError:
            return
        self._buffer_task = None
        self._flush_buffer()

    async def _run_loop(self):
        """Drain-everything wave loop with CONTINUOUS BATCHING. Each
        iteration collects ALL queued job groups. Batchable jobs join
        the ROLLING device bucket shared across waves; it flushes when
        it reaches the device-ingest gate (full), when non-batchable
        work dispatches anyway (merged — the trickle rides along for
        free), or when the oldest job's latency budget expires
        (deadline task). Flushed waves are prepped + dispatched, then
        finalized (readback + retries) in a separate task so the next
        wave's host prep overlaps this wave's device execution — the
        TPU analog of prepareWork re-filling idle workers
        (index.ts:357-534)."""
        while not self._closed:
            _, _, jobs = await self._queue.get()
            # from here until the jobs land in _rolling or in
            # _dispatch_wave they live only in this local — count the
            # window as dispatching so a cross-thread is_quiescent()
            # (drift monitor) can never see a falsely idle verifier
            self._dispatching += 1
            try:
                jobs = list(jobs)
                while True:
                    try:
                        _, _, more = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    jobs.extend(more)
                self.metrics.queue_length = self._queue.qsize()
                immediate: list[_Job] = []
                for j in jobs:
                    if j.batchable and self._latency_budget > 0:
                        self._rolling.append(j)
                        self._rolling_sets += len(j.sets)
                    else:
                        immediate.append(j)
                self.metrics.rolling_sets = self._rolling_sets
                if immediate:
                    if self._rolling:
                        self.metrics.rolling_flushes["merged"] += 1
                    await self._dispatch_wave(
                        immediate + self._take_rolling()
                    )
                elif self._rolling_sets >= self._flush_target():
                    self.metrics.rolling_flushes["full"] += 1
                    await self._dispatch_wave(self._take_rolling())
                elif self._rolling:
                    self._arm_rolling_deadline()
            finally:
                self._dispatching -= 1

    def _take_rolling(self) -> list[_Job]:
        jobs, self._rolling = self._rolling, []
        self._rolling_sets = 0
        self.metrics.rolling_sets = 0
        if self._rolling_task is not None:
            self._rolling_task.cancel()
            self._rolling_task = None
        return jobs

    def _arm_rolling_deadline(self):
        """Schedule the deadline flush for the OLDEST rolling job:
        enqueue time + latency budget (the budget rides on top of the
        100 ms gossip buffer the job may already have waited in)."""
        if self._rolling_task is not None:
            return
        oldest = min(j.enqueued_at for j in self._rolling)
        delay = max(
            0.0, oldest + self._latency_budget - time.monotonic()
        )
        self._rolling_task = asyncio.ensure_future(
            self._rolling_flush_after(delay)
        )

    async def _rolling_flush_after(self, delay: float):
        try:
            await asyncio.sleep(delay)
        except asyncio.CancelledError:
            return
        self._rolling_task = None
        if self._rolling:
            self.metrics.rolling_flushes["deadline"] += 1
            await self._dispatch_wave(self._take_rolling())

    async def _dispatch_wave(self, jobs: list[_Job]):
        """Admit one wave into the overlapped pipeline. The wave's
        prep + dispatch runs as its own task (_run_wave) so the run
        loop returns to draining the queue immediately — wave N+1's
        host prep overlaps wave N's device execution. Admission is
        bounded by the pipeline-depth knob: depth d allows d-1 waves
        in the prefetch window ahead of the finalizing wave; depth 1
        awaits the wave inline (the pre-pipeline synchronous
        behavior)."""
        if not jobs:
            return
        self.metrics.waves += 1
        t0 = time.monotonic()
        for j in jobs:
            self.metrics.total_job_wait_s += t0 - j.enqueued_at
        self._dispatching += 1
        try:
            depth = self._pipeline_depth
            try:
                while depth > 1 and len(self._wave_tasks) >= depth - 1:
                    await asyncio.wait(
                        set(self._wave_tasks),
                        return_when=asyncio.FIRST_COMPLETED,
                    )
            except asyncio.CancelledError:
                self._fail_jobs(
                    jobs, RuntimeError("BLS verifier closed")
                )
                raise
            task = asyncio.ensure_future(self._run_wave(jobs, t0))
            self._wave_tasks.add(task)
            task.add_done_callback(self._wave_done(jobs))
            self._occupancy_mark()
            if depth <= 1:
                await task
        finally:
            self._dispatching -= 1

    def _wave_done(self, jobs: list[_Job]):
        """Done-callback for a pipeline wave task: drop it from the
        prefetch window, and fail its jobs if the task was cancelled
        before its own CancelledError handler could run (close() can
        cancel a task that never started executing)."""

        def cb(task: asyncio.Task):
            self._wave_tasks.discard(task)
            if task.cancelled():
                self._fail_jobs(
                    jobs, RuntimeError("BLS verifier closed")
                )
            self._occupancy_mark()

        return cb

    def _finalizer_done(self, task: asyncio.Task):
        self._finalizers.discard(task)
        self._occupancy_mark()

    async def _run_wave(self, jobs: list[_Job], t0: float):
        """Prep + dispatch one wave; finalize (readback + retries) in
        a separate task so readback of wave N overlaps compute of
        N+1. Prep seconds spent while another wave was already in
        flight are credited to prep_overlap_hidden_s — host time the
        pipeline hid behind device execution instead of serializing
        ahead of it."""
        overlapped = self._inflight() > 1  # this task counts as one
        tp = time.monotonic()
        try:
            wave = await self._await_device(
                self._prep_and_dispatch(jobs)
            )
        except asyncio.CancelledError:
            self._fail_jobs(jobs, RuntimeError("BLS verifier closed"))
            raise
        except asyncio.TimeoutError:
            # wave watchdog: the dispatch overran the deadline-class
            # budget (a hung device program). Trip the tracker and
            # resolve every pending verdict on the host oracle — the
            # callers get correct verdicts, not a timeout error.
            if self._health is not None:
                self._health.note_watchdog_trip("deadline")
            await self._failover_jobs(jobs)
            return
        except Exception as e:
            # device-error taxonomy (device/health.py): classify;
            # programming errors propagate to the waiters (our bug,
            # not the device's), device kinds report to the tracker
            # and the waiters get host-oracle verdicts instead
            if not await self._handle_wave_error(e, jobs):
                self._fail_jobs(jobs, e)
            return
        if overlapped:
            self.metrics.prep_overlap_hidden_s += (
                time.monotonic() - tp
            )
        task = asyncio.ensure_future(self._finalize_wave(wave, t0))
        self._finalizers.add(task)
        task.add_done_callback(self._finalizer_done)

    def _fail_jobs(self, jobs, err):
        for j in jobs:
            if not j.future.done():
                j.future.set_exception(err)

    def _resolve_job(self, j: _Job, ok: bool):
        """Resolve a job's future and record submit-to-verdict
        latency (the continuous-batching SLO: gossip buffer + rolling
        wait + device time, end to end)."""
        if not j.future.done():
            j.future.set_result(ok)
            if j.created_at:
                self.metrics.verify_latency.observe(
                    time.monotonic() - j.created_at
                )

    # -- device fault domain (device/health.py) -------------------------

    async def _await_device(self, coro):
        """Apply the armed wave-watchdog deadline (attach_health) to
        one device-bound await; pass-through when unarmed."""
        if self._wave_timeout_s is None or self._health is None:
            return await coro
        return await asyncio.wait_for(coro, timeout=self._wave_timeout_s)

    async def _handle_wave_error(self, e, jobs) -> bool:
        """Taxonomy routing for a failed wave: returns True when the
        jobs were resolved via host failover, False when the caller
        must propagate the error to the waiters (programming errors —
        TypeError/KeyError from our own code must surface as the bug
        they are — or no tracker attached, the legacy behavior)."""
        health = self._health
        if health is None:
            return False
        kind = classify_device_error(e)
        if kind == "programming":
            return False
        try:
            health.record_fault(kind, client="bls")
        except ValueError:
            return False
        if health.should_log("bls"):
            self._health_log().warn(
                "device wave failed; verdicts riding host oracle",
                {"kind": kind, "err": repr(e)},
            )
        await self._failover_jobs(jobs)
        return True

    async def _failover_jobs(self, jobs) -> None:
        """Resolve every still-pending job with HOST-ORACLE verdicts
        computed from its raw signature sets — exact per-set pairing
        checks, bit-identical to OracleBlsVerifier (and to the device
        path: the differential suite proves all three agree). Zero
        lost verdicts, zero wrong verdicts; runs in the prep pool so
        the ~ms-per-set pairing math stays off the event loop."""
        loop = asyncio.get_event_loop()
        live = [j for j in jobs if not j.future.done()]
        if not live:
            return
        if self._health is not None:
            self._health.note_failover("bls")

        def verdicts():
            return [_host_oracle_sets(j.sets) for j in live]

        out = await loop.run_in_executor(self._prep_pool, verdicts)
        for j, ok in zip(live, out):
            self._resolve_job(j, bool(ok))

    async def _prep_and_dispatch(self, jobs: list[_Job]):
        """Host prep (thread pool, parallel per job) + bucket packing +
        STREAMING async device dispatch: buckets are packed by set
        counts up front (no prep needed), and each bucket is built and
        dispatched the moment its jobs' preps complete — so host prep
        of bucket k+1 overlaps device execution of bucket k instead of
        serializing ahead of the whole wave (the round-4 wave prepped
        ALL jobs before the first dispatch, leaving the device idle for
        the entire prep phase). Returns (buckets, device verdicts)."""
        loop = asyncio.get_event_loop()

        def prep_job(j: _Job):
            try:
                prepared = [self._prepare(s) for s in j.sets]
            except api.InvalidPointError:
                return None
            if any(p is None for p in prepared):
                return None
            return prepared

        # first device dispatch of the wave: the device interval the
        # jobs' device_wave spans report starts here, not at wave t0
        # (host prep ahead of it must not masquerade as device time)
        first_dispatch: list[float | None] = [None]

        prep_futs: dict[int, asyncio.Future] = {}
        live: list[_Job] = []
        for j in jobs:
            if len(j.sets) == 0:
                # empty set list: vacuously true, and it would carry no
                # bucket parts — _finalize_wave would never resolve it
                self._resolve_job(j, True)
                continue
            prep_futs[id(j)] = loop.run_in_executor(
                self._prep_pool, prep_job, j
            )
            live.append(j)
        # pack into device buckets by COUNT, preserving job boundaries;
        # a job larger than one bucket (a 64-block sync segment carries
        # ~8,000 sets, index.ts:51) is split into parts whose verdicts
        # AND together
        packing: list[list[tuple[_Job, int, int]]] = []  # (job, off, n)
        cur: list[tuple[_Job, int, int]] = []
        cur_n = 0
        cap = self._bucket_cap()
        for j in live:
            total, off = len(j.sets), 0
            while off < total:
                take = min(total - off, cap - cur_n)
                if take == 0:
                    packing.append(cur)
                    cur, cur_n = [], 0
                    continue
                cur.append((j, off, take))
                cur_n += take
                off += take
                if cur_n >= cap:
                    packing.append(cur)
                    cur, cur_n = [], 0
        if cur:
            packing.append(cur)
        self.metrics.jobs_started += len(live)

        async def run_bucket(plan):
            parts: list[tuple[_Job, list]] = []
            for j, off, take in plan:
                p = await prep_futs[id(j)]
                if p is None:
                    # malformed on host -> the job fails without
                    # device work (maybeBatch.ts:17-44 semantics)
                    self._resolve_job(j, False)
                    continue
                if j.future.done():
                    # already failed by another bucket's host
                    # pre-validation — don't ship dead sets
                    continue
                j.prepared = p
                parts.append((j, p[off : off + take]))
            if not parts:
                return None
            sets = [s for _, part in parts for s in part]
            b = kernels.bucket_size(len(sets))
            if not self._use_ingest(b) and len(parts) > 1:
                # HOST-path bucket spanning several jobs: one
                # signature that fails host decompression (no sqrt /
                # not in subgroup) used to scalar-False the WHOLE
                # bucket, fanning every innocent job out through the
                # per-job/per-set retry ladder. Pre-validate with the
                # same cached decompression the host path runs anyway:
                # fail only the owning jobs, dispatch the rest.
                def find_bad() -> set[int]:
                    out: set[int] = set()
                    for j, part in parts:
                        if id(j) in out:
                            continue
                        if any(
                            not self._host_sig_valid(s)
                            for s in part
                        ):
                            out.add(id(j))
                    return out

                # cached C decompression, but still ~0.5 ms/sig cold —
                # keep it off the event loop
                bad = await loop.run_in_executor(
                    self._prep_pool, find_bad
                )
                if bad:
                    self.metrics.host_invalid_jobs += len(bad)
                    for j, _part in parts:
                        if id(j) in bad:
                            self._resolve_job(j, False)
                    parts = [
                        (j, p) for j, p in parts if id(j) not in bad
                    ]
                    if not parts:
                        return None
                    sets = [s for _, part in parts for s in part]
            if first_dispatch[0] is None:
                first_dispatch[0] = time.monotonic()
            ok = await loop.run_in_executor(
                None, self._submit_bucket, sets
            )
            self.metrics.buckets_dispatched += 1
            return parts, ok

        results = await asyncio.gather(
            *(run_bucket(plan) for plan in packing)
        )
        buckets = [r[0] for r in results if r is not None]
        oks = [r[1] for r in results if r is not None]
        return buckets, oks, first_dispatch[0]

    def _host_sig_valid(self, s: "_PreparedSet") -> bool:
        """Does this set's signature survive host decompression? Uses
        the same lru-cached decompression the host dispatch path runs,
        so pre-validation costs nothing extra."""
        try:
            sig = (
                api.decompress_signature(s.sig_raw)
                if s.sig_raw
                else api.decompress_signature_parsed(
                    s.sig_x, s.sig_sign
                )
            )
        except api.InvalidPointError:
            return False
        return sig is not None

    async def _finalize_wave(self, wave, t0: float):
        """One readback for the whole wave; failed buckets retry
        per job, then per set (worker.ts:88-103 isolation)."""
        buckets, oks, t_dispatch = wave
        try:
            verdicts = await self._await_device(self._readback(oks))
            # verdicts are on host: the device work for every job in
            # the wave is done — stamp the first-dispatch-to-readback
            # interval on each job so its awaiting caller can graft a
            # device child span (host prep ahead of the first dispatch
            # is excluded: it must not masquerade as device time)
            if t_dispatch is not None:
                dt_dev = time.monotonic() - t_dispatch
                for b in buckets:
                    for j, _part in b:
                        j.device_s = dt_dev
            # a job's direct verdict is the AND over every bucket part
            # that carried its sets
            job_ok: dict[int, bool] = {}
            job_of: dict[int, _Job] = {}
            shared: set[int] = set()  # jobs whose bucket carried others
            for b, ok in zip(buckets, verdicts):
                for j, _part in b:
                    jid = id(j)
                    job_of[jid] = j
                    job_ok[jid] = job_ok.get(jid, True) and ok
                    if len(b) > 1:
                        shared.add(jid)
            retry: list[_Job] = []
            for jid, ok in job_ok.items():
                j = job_of[jid]
                if j.future.done():
                    # pre-failed by host pre-validation — even if its
                    # other (all-good) buckets came back True, the job
                    # is failed and its sets must not count as batch
                    # successes
                    pass
                elif ok:
                    self.metrics.batch_sigs_success += len(j.prepared)
                    self._resolve_job(j, True)
                elif len(j.prepared) == 1 and jid not in shared:
                    # alone in its bucket: the aggregate verdict IS the
                    # job's own. In a SHARED bucket (rolling/merged
                    # trickle) a failed aggregate says nothing about
                    # this job — retry it like any multi-set job, or
                    # one bad pairing fails every innocent 1-set rider.
                    self._resolve_job(j, False)
                else:
                    retry.append(j)
            if retry:
                self.metrics.batch_retries += 1
                verdicts = await self._await_device(
                    self._verdict_wave([j.prepared for j in retry])
                )
                per_set: list[_Job] = []
                for j, ok in zip(retry, verdicts):
                    if ok:
                        self._resolve_job(j, True)
                    elif len(j.prepared) == 1:
                        self._resolve_job(j, False)
                    else:
                        per_set.append(j)
                if per_set:
                    flat = [
                        [s]
                        for j in per_set
                        for s in j.prepared
                    ]
                    singles = await self._await_device(
                        self._verdict_wave(flat)
                    )
                    i = 0
                    for j in per_set:
                        n = len(j.prepared)
                        self._resolve_job(
                            j, all(singles[i : i + n])
                        )
                        i += n
        except asyncio.CancelledError:
            self._fail_jobs(
                [j for b in buckets for j, _ in b],
                RuntimeError("BLS verifier closed"),
            )
            raise
        except asyncio.TimeoutError:
            # wave watchdog: readback (or a retry dispatch) stuck past
            # the deadline-class budget — trip the tracker and resolve
            # the pending verdicts on the host oracle
            if self._health is not None:
                self._health.note_watchdog_trip("deadline")
            await self._failover_jobs(
                [j for b in buckets for j, _ in b]
            )
        except Exception as e:
            # taxonomy routing (device/health.py): device kinds fail
            # over to host-oracle verdicts; programming errors (and
            # tracker-less verifiers) propagate to the waiters
            jobs = [j for b in buckets for j, _ in b]
            if not await self._handle_wave_error(e, jobs):
                self._fail_jobs(jobs, e)
        finally:
            dt = time.monotonic() - t0
            self.metrics.total_device_time_s += dt
            n_sets = sum(
                len(part) for b in buckets for _, part in b
            )
            self.metrics.last_wave_sets = n_sets
            self.metrics.wave_sets_total += n_sets
            self.metrics.last_wave_duration_s = dt

    def _submit_bucket(self, sets: list[_PreparedSet]):
        """Pad to a bucket size, build device arrays (sharded over the
        mesh when even), dispatch WITHOUT readback. Returns the device
        () bool. Signatures/messages ship compressed — decompression
        and hash-to-G2 run inside the device program."""
        from ..ops import tower

        n = len(sets)
        b = kernels.bucket_size(n)
        health = self._health
        if health is not None and not health.device_allowed():
            # device quarantined: bit-identical host-oracle verdict,
            # no jax array is built (touching a sick TPU could hang
            # the failover itself). Plain bool — _readback handles it.
            self._count_dispatch(b, False, failover=True)
            if health.note_failover("bls"):
                self._health_log().warn(
                    "device quarantined: buckets riding host oracle",
                    {"state": health.state.value},
                )
            return _host_oracle_prepared(sets)
        pad = b - n
        pad_set = _pad_prepared()
        full = sets + [pad_set] * pad
        rand = _rand_scalars(b)
        pk_dev = C.g1_batch_from_ints([s.pk for s in full])
        bits = C.scalars_to_bits(rand, kernels.RAND_BITS)
        mask = jnp.asarray([True] * n + [False] * pad)
        mesh = self._mesh
        shard = (
            mesh is not None and b % mesh.devices.size == 0
        )
        use_ingest = self._use_ingest(b)
        self._count_dispatch(b, use_ingest)
        if use_ingest:
            # device ingest: compressed signatures + field draws in
            sig_x = tower.fq2_from_ints([s.sig_x for s in full])
            sig_sign = jnp.asarray([s.sig_sign for s in full])
            u0 = tower.fq2_from_ints([s.draws[0] for s in full])
            u1 = tower.fq2_from_ints([s.draws[1] for s in full])
            if shard:
                # WHOLE-BUCKET mesh path (ISSUE 16): each chip runs
                # the complete collective-free verify on the
                # sub-bucket it owns; the only collective is the one
                # verdict psum inside the shard_map program. Mesh
                # programs bypass the warm registry (distinct
                # executables from the single-host ones).
                from .. import parallel

                pk_dev = parallel.shard_batch(mesh, pk_dev)
                sig_x = parallel.shard_batch(mesh, sig_x)
                sig_sign = parallel.shard_batch(mesh, sig_sign)
                u0 = parallel.shard_batch(mesh, u0)
                u1 = parallel.shard_batch(mesh, u1)
                bits = parallel.shard_batch(mesh, bits)
                mask = parallel.shard_batch(mesh, mask)
                _device.record_transfer(
                    "h2d", pk_dev, sig_x, sig_sign, u0, u1, bits, mask
                )
                return kernels.run_verify_batch_ingest_mesh(
                    mesh, pk_dev, sig_x, sig_sign, u0, u1, bits, mask
                )
            _device.record_transfer(
                "h2d", pk_dev, sig_x, sig_sign, u0, u1, bits, mask
            )
            out = kernels.run_verify_batch_ingest_async(
                pk_dev, sig_x, sig_sign, u0, u1, bits, mask
            )
            # the jit compile (or persistent-cache load) happened on
            # this dispatch — later buckets of size b are warm
            kernels.mark_ingest_warm(b)
            return out
        # small buckets: host decompression/hashing (cached C calls —
        # affordable at this scale, and it avoids compiling the ingest
        # stages for every small bucket size)
        hs, sigs = [], []
        ok = True
        for s in full:
            try:
                sig = (
                    api.decompress_signature(s.sig_raw)
                    if s.sig_raw
                    else api.decompress_signature_parsed(
                        s.sig_x, s.sig_sign
                    )
                )
            except api.InvalidPointError:
                sig = None
            if sig is None:
                ok = False
                sig = oc.G2_GEN
            sigs.append(sig)
            hs.append(
                api.message_to_g2(s.msg_raw)
                if s.msg_raw
                else api.draws_to_g2(s.draws)
            )
        if not ok:
            # an invalid signature fails the bucket without device work
            import jax.numpy as _jnp

            return _jnp.asarray(False)
        h_dev = C.g2_batch_from_ints(hs)
        sig_dev = C.g2_batch_from_ints(sigs)
        h = (h_dev.x, h_dev.y)
        if shard:
            # whole-bucket mesh verify (one collective: verdict psum)
            from .. import parallel

            pk_dev = parallel.shard_batch(mesh, pk_dev)
            h = parallel.shard_batch(mesh, h)
            sig_dev = parallel.shard_batch(mesh, sig_dev)
            bits = parallel.shard_batch(mesh, bits)
            mask = parallel.shard_batch(mesh, mask)
            _device.record_transfer(
                "h2d", pk_dev, h, sig_dev, bits, mask
            )
            return kernels.run_verify_batch_mesh(
                mesh, pk_dev, h, sig_dev, bits, mask
            )
        _device.record_transfer("h2d", pk_dev, h, sig_dev, bits, mask)
        return kernels.run_verify_batch_async(
            pk_dev, h, sig_dev, bits, mask
        )

    async def _readback(self, oks) -> list[bool]:
        """ONE host transfer for a wave of device verdicts."""
        loop = asyncio.get_event_loop()

        def read():
            import numpy as np

            if not oks:
                return []
            if all(isinstance(v, bool) for v in oks):
                # all-failover wave: the verdicts are host bools
                # already — don't build a device array just to read
                # it back (and don't touch a quarantined chip at all)
                return list(oks)
            _device.record_transfer("d2h", oks)
            if len(oks) == 1:
                return [bool(oks[0])]
            return [bool(v) for v in np.asarray(jnp.stack(oks))]

        return await loop.run_in_executor(None, read)

    async def _verdict_wave(
        self, groups: list[list[_PreparedSet]]
    ) -> list[bool]:
        """Verify each group as its own bucket; all dispatched before
        one readback."""
        loop = asyncio.get_event_loop()
        out: list[bool] = []
        # split oversized groups (a 64-block sync segment can carry
        # ~8,000 sets, index.ts:51) into AND-ed device buckets
        plan: list[tuple[int, int]] = []  # (group idx, n buckets)
        buckets: list[list[_PreparedSet]] = []
        cap = self._bucket_cap()
        for gi, g in enumerate(groups):
            parts = [
                g[i : i + cap] for i in range(0, len(g), cap)
            ] or [[]]
            plan.append((gi, len(parts)))
            buckets.extend(parts)

        def dispatch():
            return [
                self._submit_bucket(b) if b else None
                for b in buckets
            ]

        oks = await loop.run_in_executor(None, dispatch)
        live = [o for o in oks if o is not None]
        verdicts_flat = await self._readback(live) if live else []
        it = iter(verdicts_flat)
        flat = [True if o is None else next(it) for o in oks]
        i = 0
        for _, nparts in plan:
            out.append(all(flat[i : i + nparts]))
            i += nparts
        return out

    async def _run_batch(self, sets: list[_PreparedSet]) -> bool:
        return (await self._verdict_wave([sets]))[0]

    async def _run_same_message(self, pairs, h) -> bool:
        """One fused aggregate+pairing check; splits above the device
        cap and ANDs (random weights keep each part sound). pairs:
        (pk_ints, (xc0, xc1), sign) triples — signature decompression
        happens on device."""
        cap = self._bucket_cap()
        if len(pairs) > cap:
            parts = [
                pairs[i : i + cap] for i in range(0, len(pairs), cap)
            ]
            verdicts = await asyncio.gather(
                *(self._run_same_message(p, h) for p in parts)
            )
            return all(verdicts)
        loop = asyncio.get_event_loop()

        def dispatch():
            from ..ops import tower

            n = len(pairs)
            b = kernels.bucket_size(n)
            health = self._health
            if health is not None and not health.device_allowed():
                # quarantined: exact host pairing checks against the
                # one already-hashed message point (bit-identical)
                self._count_dispatch(b, False, failover=True)
                if health.note_failover("bls"):
                    self._health_log().warn(
                        "device quarantined: same-message riding"
                        " host oracle",
                        {"state": health.state.value},
                    )
                return _host_oracle_same_message(pairs, h)
            pad = b - n
            pad_set = _pad_prepared()
            rand = _rand_scalars(b)
            pks = [p for p, _, _ in pairs] + [pad_set.pk] * pad
            pk_dev = C.g1_batch_from_ints(pks)
            h_dev = C.g2_batch_from_ints([h])  # batch (1,)
            bits = C.scalars_to_bits(rand, kernels.RAND_BITS)
            mask = jnp.asarray([True] * n + [False] * pad)
            use_ingest = self._use_ingest(b, "same_message")
            self._count_dispatch(b, use_ingest)
            if use_ingest:
                sxs = [x for _, x, _ in pairs] + [
                    pad_set.sig_x
                ] * pad
                sgs = [s for _, _, s in pairs] + [
                    pad_set.sig_sign
                ] * pad
                sig_x = tower.fq2_from_ints(sxs)
                sig_sign = jnp.asarray(sgs)
                mesh = self._mesh
                if (
                    mesh is not None
                    and b % mesh.devices.size == 0
                ):
                    # whole-bucket mesh: the (1,)-batch hash point is
                    # replicated (every shard pairs its aggregate
                    # against the same H); one verdict psum
                    from .. import parallel

                    pk_s = parallel.shard_batch(mesh, pk_dev)
                    sig_x_s = parallel.shard_batch(mesh, sig_x)
                    sign_s = parallel.shard_batch(mesh, sig_sign)
                    bits_s = parallel.shard_batch(mesh, bits)
                    mask_s = parallel.shard_batch(mesh, mask)
                    h_r = parallel.replicate(
                        mesh, (h_dev.x, h_dev.y)
                    )
                    _device.record_transfer(
                        "h2d", pk_s, h_r, sig_x_s, sign_s,
                        bits_s, mask_s,
                    )
                    return kernels.run_verify_same_message_mesh(
                        mesh, pk_s, h_r, sig_x_s, sign_s,
                        bits_s, mask_s,
                    )
                _device.record_transfer(
                    "h2d", pk_dev, h_dev, sig_x, sig_sign, bits, mask
                )
                out = kernels.run_verify_same_message_ingest_async(
                    pk_dev,
                    (h_dev.x, h_dev.y),
                    sig_x,
                    sig_sign,
                    bits,
                    mask,
                )
                kernels.mark_ingest_warm(b, "same_message")
                return out
            # small groups: host decompression (cached C), avoiding a
            # per-bucket-size ingest-stage compile on the gossip path
            sigs = []
            for _, sx, sg in pairs:
                sig = api.decompress_signature_parsed(sx, sg)
                if sig is None:
                    return jnp.asarray(False)
                sigs.append(sig)
            sigs += [
                api.decompress_signature_parsed(
                    pad_set.sig_x, pad_set.sig_sign
                )
            ] * pad
            sig_dev = C.g2_batch_from_ints(sigs)
            _device.record_transfer(
                "h2d", pk_dev, h_dev, sig_dev, bits, mask
            )
            return kernels.run_verify_same_message(
                pk_dev, (h_dev.x, h_dev.y), sig_dev, bits, mask
            )

        ok = await loop.run_in_executor(None, dispatch)
        return bool((await self._readback([ok]))[0])


class OracleBlsVerifier:
    """Single-threaded oracle-backed verifier — same interface, used in
    tests and as the differential reference (reference analog:
    BlsSingleThreadVerifier, chain/bls/singleThread.ts:8)."""

    def can_accept_work(self) -> bool:
        return True

    async def verify_signature_sets(
        self, sets, batchable=False, priority=False
    ) -> bool:
        from ..crypto.bls import pairing as op
        from ..metrics.tracing import child_span

        try:
            with child_span("bls_verify_job"):
                for s in sets:
                    pk = api.decompress_pubkey(s.pubkey)
                    h = api.message_to_g2(s.message)
                    sig = api.decompress_signature(s.signature)
                    if sig is None:
                        return False
                    ok = op.pairing_product_is_one(
                        [(pk, h), (oc.g1_neg(oc.G1_GEN), sig)]
                    )
                    if not ok:
                        return False
                return True
        except api.InvalidPointError:
            return False

    async def verify_signature_sets_same_message(self, sets, message):
        from ..crypto.bls import pairing as op

        h = api.message_to_g2(message)
        out = []
        for s in sets:
            try:
                pk = api.decompress_pubkey(s.pubkey)
                sig = api.decompress_signature(s.signature)
            except api.InvalidPointError:
                out.append(False)
                continue
            if sig is None:
                out.append(False)
                continue
            out.append(
                op.pairing_product_is_one(
                    [(pk, h), (oc.g1_neg(oc.G1_GEN), sig)]
                )
            )
        return out

    async def close(self):
        pass

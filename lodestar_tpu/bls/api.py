"""Signature-set model for the verifier service.

Reference analog: `ISignatureSet` in
state-transition/src/signatureSets/types.ts and the serialized
`SerializedSet {message, publicKey, signature}` the BLS pool ships to
workers (chain/bls/multithread/types.ts). A set is one independently
verifiable (aggregate-pubkey, message, signature) triple; same-message
batches carry k (pubkey, signature) pairs over one message
(chain/bls/multithread/jobItem.ts:50-92).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

from ..crypto.bls import curve as oc
from ..crypto.bls import hash_to_curve as h2c
from ..crypto.bls.signature import BLS_DST_SIG


@dataclass(frozen=True)
class SignatureSet:
    """One verification unit: aggregate pubkey point, 32-byte signing
    root, 96-byte compressed signature."""

    pubkey: bytes  # 48-byte compressed G1 (possibly pre-aggregated)
    message: bytes  # signing root
    signature: bytes  # 96-byte compressed G2


@dataclass(frozen=True)
class SameMessageSet:
    """One (pubkey, signature) pair of a same-message batch."""

    pubkey: bytes
    signature: bytes


class InvalidPointError(ValueError):
    pass


@lru_cache(maxsize=65536)
def decompress_pubkey(pk: bytes):
    """48B compressed -> affine ints; rejects infinity (spec
    KeyValidate) and off-curve/subgroup points. Cached: validator
    pubkeys recur constantly (reference pubkey-index-map, SURVEY.md
    §2.1). Native backend (csrc/bls381.c) fuses decode + on-curve +
    subgroup check."""
    from ..crypto.bls import native

    if native.available():
        try:
            p = native.g1_decompress(pk)
        except native.NativeError as e:
            raise InvalidPointError(str(e)) from e
        if p is None:
            raise InvalidPointError("pubkey is the identity")
        return p
    try:
        p = oc.g1_from_bytes(pk)
    except Exception as e:  # malformed encoding
        raise InvalidPointError(str(e)) from e
    if p is None:
        raise InvalidPointError("pubkey is the identity")
    if not oc.g1_in_subgroup(p):
        raise InvalidPointError("pubkey not in G1 subgroup")
    return p


@lru_cache(maxsize=16384)
def decompress_signature(sig: bytes):
    """96B compressed -> affine ints on the twist; identity -> None
    (an identity signature can only verify for identity pubkeys, which
    KeyValidate already rejects — callers treat None as invalid)."""
    from ..crypto.bls import native

    if native.available():
        try:
            return native.g2_decompress(sig)
        except native.NativeError as e:
            raise InvalidPointError(str(e)) from e
    try:
        q = oc.g2_from_bytes(sig)
    except Exception as e:
        raise InvalidPointError(str(e)) from e
    if q is None:
        return None
    if not oc.g2_in_subgroup(q):
        raise InvalidPointError("signature not in G2 subgroup")
    return q


@lru_cache(maxsize=8192)
def message_to_g2(message: bytes, dst: bytes = BLS_DST_SIG):
    """Hash a signing root to G2 (host SHA-256 path). Cached because the
    gossip batch path groups many sets on one attestation data
    (IndexedGossipQueueMinSize, SURVEY.md §2.2)."""
    return h2c.hash_to_g2(message, dst)


@lru_cache(maxsize=16384)
def message_draws(message: bytes, dst: bytes = BLS_DST_SIG):
    """Host half of DEVICE hash-to-G2: expand_message_xmd + reduction
    to two Fq2 draws (microseconds); the SSWU/isogeny/cofactor field
    work runs batched on the TPU (ops/ingest.py)."""
    u0, u1 = h2c.hash_to_field_fq2(message, dst, 2)
    return u0, u1


@lru_cache(maxsize=16384)
def decompress_signature_parsed(sig_x: tuple, sign: bool):
    """Host decompression from a parsed (xc0, xc1, sign) triple — the
    small-bucket path where device ingest isn't warranted. Returns
    affine ints or None (not on curve / subgroup)."""
    from ..crypto.bls import fields as F
    from ..crypto.bls.curve import g2_in_subgroup

    xc0, xc1 = sig_x
    x = (xc0, xc1)
    rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), (4, 4))
    y = F.fq2_sqrt(rhs)
    if y is None:
        return None
    # spec sign rule: a_flag reflects y_im (or y_re when y_im == 0)
    half = (F.P - 1) // 2
    computed = (y[1] > half) if y[1] != 0 else (y[0] > half)
    if computed != sign:
        y = F.fq2_neg(y)
    p = (x, y)
    if not g2_in_subgroup(p):
        return None
    return p


@lru_cache(maxsize=16384)
def draws_to_g2(draws: tuple):
    """Host SSWU+iso+cofactor from cached field draws (small-bucket
    path; the heavy expand_message_xmd half is already done)."""
    from ..crypto.bls import hash_to_curve as h2c
    from ..crypto.bls.curve import g2_add, g2_clear_cofactor

    u0, u1 = draws
    q0 = h2c.iso_map_g2(h2c.map_to_curve_sswu(u0))
    q1 = h2c.iso_map_g2(h2c.map_to_curve_sswu(u1))
    return g2_clear_cofactor(g2_add(q0, q1))


@lru_cache(maxsize=16384)
def parse_signature(sig: bytes):
    """96B compressed G2 -> (xc0, xc1, sign, host_ok) without the
    expensive sqrt/subgroup work (that runs on device). host_ok False
    covers malformed flags, non-canonical coordinates, and the
    infinity encoding."""
    from ..ops import ingest

    return ingest.parse_g2_compressed(sig)



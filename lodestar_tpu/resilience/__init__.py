"""Resilience layer: retries, circuit breakers, engine-state tracking.

Reference analogs: the retry/timeout wrapper of JsonRpcHttpClient
(eth1/provider/jsonRpcHttpClient.ts:76), the engine API's
ONLINE/OFFLINE/SYNCING/AUTH_FAILED availability machine
(execution/engine/http.ts), and the builder flow's missed-slot circuit
breaker (`faultInspectionWindow`/`allowedFaults`). Every external
dependency path — engine API, builder relay, eth1 polling, checkpoint
and range sync, reqresp — routes its failure handling through these
primitives so behavior under faults is uniform, observable on
`/metrics`, and testable with injected clocks (no wall-clock sleeps
in tests).
"""

from .breaker import (
    BREAKER_STATE_INDEX,
    BreakerState,
    CircuitBreaker,
    FaultInspectionWindow,
)
from .clock import ManualClock, SystemClock
from .engine_state import (
    ENGINE_STATE_INDEX,
    EngineStateTracker,
    ExecutionEngineState,
)
from .metrics import (
    bind_breaker,
    bind_engine_tracker,
    create_resilience_metrics,
    make_retry_hook,
)
from .retry import (
    RetryError,
    RetryOptions,
    backoff_delay,
    default_retryable,
    retry,
    retry_sync,
)

__all__ = [
    "BREAKER_STATE_INDEX",
    "BreakerState",
    "CircuitBreaker",
    "ENGINE_STATE_INDEX",
    "EngineStateTracker",
    "ExecutionEngineState",
    "FaultInspectionWindow",
    "ManualClock",
    "RetryError",
    "RetryOptions",
    "SystemClock",
    "backoff_delay",
    "bind_breaker",
    "bind_engine_tracker",
    "create_resilience_metrics",
    "default_retryable",
    "make_retry_hook",
    "retry",
    "retry_sync",
]

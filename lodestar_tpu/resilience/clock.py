"""Injectable clocks for the resilience primitives.

Every retry delay, breaker reset window, and poll backoff in this
package reads time through one of these objects instead of the `time` /
`asyncio` modules directly, so unit tests drive schedules with
`ManualClock` and never wall-clock sleep (the reference achieves the
same with sinon fake timers in its retry/backoff unit tests).
"""

from __future__ import annotations

import asyncio
import time


class SystemClock:
    """Real time: `time.monotonic` + `asyncio.sleep`/`time.sleep`."""

    def monotonic(self) -> float:
        return time.monotonic()

    async def sleep(self, seconds: float) -> None:
        if seconds > 0:
            await asyncio.sleep(seconds)

    def sleep_sync(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class ManualClock:
    """Deterministic test clock: sleeps advance virtual time instantly
    and are recorded, `advance()` moves time for breaker windows."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)
        self.sleeps: list[float] = []

    def monotonic(self) -> float:
        return self.now

    async def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self.now += max(0.0, seconds)

    def sleep_sync(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self.now += max(0.0, seconds)

    def advance(self, seconds: float) -> None:
        self.now += seconds


SYSTEM_CLOCK = SystemClock()

"""Resilience metric family + binding helpers.

Registers retry counters, breaker-state gauges, and the engine-state
gauge on the node's existing RegistryMetricCreator so they ride the
same `/metrics` endpoint as the lodestar catalog (metrics/beacon.py).
`bind_breaker` / `bind_engine_tracker` attach the live objects'
transition hooks to the gauges so scrapes always see current state.
"""

from __future__ import annotations

from types import SimpleNamespace

from .breaker import BREAKER_STATE_INDEX
from .engine_state import ENGINE_STATE_INDEX


def create_resilience_metrics(reg) -> SimpleNamespace:
    m = SimpleNamespace()
    m.retries_total = reg.counter(
        "lodestar_resilience_retries_total",
        "Retried attempts against external dependencies",
        label_names=("client",),
    )
    m.retry_giveups_total = reg.counter(
        "lodestar_resilience_retry_giveups_total",
        "Calls that exhausted all retry attempts",
        label_names=("client",),
    )
    m.breaker_state = reg.gauge(
        "lodestar_resilience_breaker_state",
        "Circuit breaker state: 0 closed, 1 open, 2 half-open",
        label_names=("name",),
    )
    m.breaker_transitions_total = reg.counter(
        "lodestar_resilience_breaker_transitions_total",
        "Circuit breaker state transitions",
        label_names=("name", "state"),
    )
    m.engine_state = reg.gauge(
        "lodestar_execution_engine_state",
        "Engine availability: 0 ONLINE, 1 SYNCED, 2 SYNCING, "
        "3 OFFLINE, 4 AUTH_FAILED",
    )
    m.engine_state_transitions_total = reg.counter(
        "lodestar_execution_engine_state_transitions_total",
        "Engine availability state transitions",
        label_names=("state",),
    )
    m.builder_faults_total = reg.counter(
        "lodestar_builder_faults_total",
        "Builder circuit-breaker faults recorded",
        label_names=("kind",),  # relay_error | missed_slot
    )
    return m


def bind_breaker(breaker, metrics) -> None:
    """Wire a CircuitBreaker/FaultInspectionWindow's transitions into
    the gauges; seeds the gauge with the current state."""
    metrics.breaker_state.set(
        BREAKER_STATE_INDEX[breaker.state], name=breaker.name
    )

    def hook(name, old, new):
        metrics.breaker_state.set(BREAKER_STATE_INDEX[new], name=name)
        metrics.breaker_transitions_total.inc(
            name=name, state=new.value
        )

    breaker.on_transition = hook


def bind_engine_tracker(tracker, metrics) -> None:
    metrics.engine_state.set(ENGINE_STATE_INDEX[tracker.state])

    def hook(old, new):
        metrics.engine_state.set(ENGINE_STATE_INDEX[new])
        metrics.engine_state_transitions_total.inc(state=new.value)

    tracker.on_transition = hook


def make_retry_hook(metrics, client: str):
    """RetryOptions.on_retry callback bumping the retry counter."""

    def hook(attempt, exc, delay):
        metrics.retries_total.inc(client=client)

    return hook

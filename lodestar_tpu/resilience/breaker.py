"""Circuit breakers: time-based (engine API) and slot-window (builder).

Two shapes, matching the two dependency profiles:

* `CircuitBreaker` — classic closed/open/half-open machine for RPC
  dependencies (the engine API): N consecutive failures open it, after
  `reset_timeout` a bounded number of half-open probes are let through,
  one success closes it again. Time comes from an injectable clock, so
  a sim can measure the window in slots and unit tests in virtual
  seconds.

* `FaultInspectionWindow` — the builder flow's breaker (reference:
  chain.ts shouldOverrideBuilder / the `faultInspectionWindow` +
  `allowedFaults` CLI knobs): faults are recorded per SLOT (missed
  proposals, relay errors); while more than `allowed_faults` slots in
  the trailing `window` carry faults the builder race is skipped and
  blocks are produced locally. When the faults age out the breaker
  goes half-open until a recorded success closes it.
"""

from __future__ import annotations

from enum import Enum

from .clock import SYSTEM_CLOCK


class BreakerState(str, Enum):
    closed = "closed"
    open = "open"
    half_open = "half_open"


# stable gauge encoding for metrics (resilience/metrics.py)
BREAKER_STATE_INDEX = {
    BreakerState.closed: 0,
    BreakerState.open: 1,
    BreakerState.half_open: 2,
}


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probes."""

    def __init__(
        self,
        name: str = "breaker",
        failure_threshold: int = 5,
        reset_timeout: float = 30.0,
        half_open_max: int = 1,
        clock=None,
        on_transition=None,  # fn(name, old: BreakerState, new)
    ):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.half_open_max = half_open_max
        self.clock = clock or SYSTEM_CLOCK
        self.on_transition = on_transition
        self.state = BreakerState.closed
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self._half_open_inflight = 0
        # full audit trail of (time, old, new) — sim assertions read it
        self.transitions: list[tuple[float, BreakerState, BreakerState]] = []

    def _transition(self, new: BreakerState) -> None:
        if new is self.state:
            return
        old = self.state
        self.state = new
        self.transitions.append((self.clock.monotonic(), old, new))
        if self.on_transition is not None:
            self.on_transition(self.name, old, new)

    def allows(self) -> bool:
        """Gate a call: True = go ahead (and report the outcome back
        via on_success/on_failure), False = fail fast."""
        if self.state is BreakerState.closed:
            return True
        if self.state is BreakerState.open:
            if (
                self.clock.monotonic() - self.opened_at
                >= self.reset_timeout
            ):
                self._transition(BreakerState.half_open)
                self._half_open_inflight = 1
                return True
            return False
        # half-open: bounded probe budget
        if self._half_open_inflight < self.half_open_max:
            self._half_open_inflight += 1
            return True
        return False

    def release_probe(self) -> None:
        """Hand back a probe slot without judging the call (the call
        was cancelled, not answered). Without this, a cancelled
        half-open probe would pin `_half_open_inflight` at the budget
        and the breaker would deny every future call."""
        if self._half_open_inflight > 0:
            self._half_open_inflight -= 1

    def on_success(self) -> None:
        self.consecutive_failures = 0
        self._half_open_inflight = 0
        self._transition(BreakerState.closed)

    def on_failure(self) -> None:
        self.consecutive_failures += 1
        self._half_open_inflight = 0
        if self.state is BreakerState.half_open or (
            self.state is BreakerState.closed
            and self.consecutive_failures >= self.failure_threshold
        ):
            self.opened_at = self.clock.monotonic()
            self._transition(BreakerState.open)


class FaultInspectionWindow:
    """Slot-window breaker for the builder race."""

    def __init__(
        self,
        name: str = "builder",
        window: int = 32,
        allowed_faults: int = 4,
        on_transition=None,
    ):
        self.name = name
        self.window = window
        self.allowed_faults = allowed_faults
        self.on_transition = on_transition
        self.fault_slots: dict[int, int] = {}  # slot -> fault count
        self.state = BreakerState.closed
        self.transitions: list[tuple[int, BreakerState, BreakerState]] = []
        self._last_slot = 0

    def _transition(self, slot: int, new: BreakerState) -> None:
        if new is self.state:
            return
        old = self.state
        self.state = new
        self.transitions.append((slot, old, new))
        if self.on_transition is not None:
            self.on_transition(self.name, old, new)

    def _faulty_slots_in_window(self, slot: int) -> int:
        lo = slot - self.window
        return sum(1 for s in self.fault_slots if lo < s <= slot)

    def _prune(self, slot: int) -> None:
        lo = slot - self.window
        for s in [s for s in self.fault_slots if s <= lo]:
            del self.fault_slots[s]

    def record_fault(self, slot: int) -> None:
        """A missed proposal or relay error at `slot`."""
        slot = int(slot)
        self._last_slot = max(self._last_slot, slot)
        self.fault_slots[slot] = self.fault_slots.get(slot, 0) + 1
        self._prune(slot)
        if self._faulty_slots_in_window(slot) > self.allowed_faults:
            self._transition(slot, BreakerState.open)

    def record_success(self, slot: int) -> None:
        """A builder block produced + accepted at `slot`."""
        slot = int(slot)
        self._last_slot = max(self._last_slot, slot)
        self._prune(slot)
        if self.state is BreakerState.half_open:
            self._transition(slot, BreakerState.closed)

    def available(self, slot: int) -> bool:
        """Should the builder race run at `slot`? Open falls back to
        local production; once faults age out of the window one probe
        bid is allowed (half-open) and a success closes the breaker."""
        slot = int(slot)
        self._last_slot = max(self._last_slot, slot)
        self._prune(slot)
        over = self._faulty_slots_in_window(slot) > self.allowed_faults
        if self.state is BreakerState.open and not over:
            self._transition(slot, BreakerState.half_open)
        elif self.state is not BreakerState.open and over:
            self._transition(slot, BreakerState.open)
        return self.state is not BreakerState.open

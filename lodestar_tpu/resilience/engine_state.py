"""Execution-engine availability state machine.

Reference analog: `ExecutionEngineState` and `getExecutionEngineState`
(execution/engine/http.ts + utils.ts in the reference): every engine
API exchange updates one of five states —

  ONLINE       reachable, no payload verdict seen yet (startup)
  SYNCED       responding and payload statuses are conclusive
  SYNCING      responding but still syncing (SYNCING/ACCEPTED verdicts)
  OFFLINE      transport failures (connection refused, timeout)
  AUTH_FAILED  HTTP 401/403 — the JWT secret is wrong; retrying with
               the same credentials cannot help

The tracker is transport-agnostic: it classifies exceptions by shape
(an `auth_failed` attribute marks auth rejections, everything else is
a transport fault) and payload statuses by the engine API verdict
enum, so the HTTP client, the in-process mock, and the sim's fault
injectors all drive the same machine.
"""

from __future__ import annotations

from enum import Enum


class ExecutionEngineState(str, Enum):
    ONLINE = "ONLINE"
    SYNCED = "SYNCED"
    SYNCING = "SYNCING"
    OFFLINE = "OFFLINE"
    AUTH_FAILED = "AUTH_FAILED"


# stable gauge encoding for metrics (resilience/metrics.py)
ENGINE_STATE_INDEX = {
    ExecutionEngineState.ONLINE: 0,
    ExecutionEngineState.SYNCED: 1,
    ExecutionEngineState.SYNCING: 2,
    ExecutionEngineState.OFFLINE: 3,
    ExecutionEngineState.AUTH_FAILED: 4,
}

# payload statuses that mean "engine is responding but not synced"
_SYNCING_STATUSES = frozenset({"SYNCING", "ACCEPTED"})
_OFFLINE_STATUSES = frozenset({"ELERROR", "UNAVAILABLE"})


class EngineStateTracker:
    """Drives ExecutionEngineState from call outcomes."""

    def __init__(self, on_transition=None):
        # on_transition(old: ExecutionEngineState, new)
        self.state = ExecutionEngineState.ONLINE
        self.on_transition = on_transition
        self.transitions: list[
            tuple[ExecutionEngineState, ExecutionEngineState]
        ] = []

    def _set(self, new: ExecutionEngineState) -> None:
        if new is self.state:
            return
        old = self.state
        self.state = new
        self.transitions.append((old, new))
        if self.on_transition is not None:
            self.on_transition(old, new)

    def on_success(self, payload_status=None) -> ExecutionEngineState:
        """A call returned. `payload_status` is the engine verdict
        string/enum for newPayload/fcU responses, None for calls that
        carry no verdict (getPayload etc. → ONLINE family only)."""
        if payload_status is None:
            if self.state in (
                ExecutionEngineState.OFFLINE,
                ExecutionEngineState.AUTH_FAILED,
            ):
                self._set(ExecutionEngineState.ONLINE)
            return self.state
        status = str(
            getattr(payload_status, "value", payload_status)
        )
        if status in _OFFLINE_STATUSES:
            self._set(ExecutionEngineState.OFFLINE)
        elif status in _SYNCING_STATUSES:
            self._set(ExecutionEngineState.SYNCING)
        else:  # VALID / INVALID / INVALID_BLOCK_HASH: conclusive
            self._set(ExecutionEngineState.SYNCED)
        return self.state

    def on_error(self, exc: BaseException) -> ExecutionEngineState:
        if getattr(exc, "auth_failed", False):
            self._set(ExecutionEngineState.AUTH_FAILED)
        else:
            self._set(ExecutionEngineState.OFFLINE)
        return self.state

    @property
    def is_online(self) -> bool:
        return self.state not in (
            ExecutionEngineState.OFFLINE,
            ExecutionEngineState.AUTH_FAILED,
        )

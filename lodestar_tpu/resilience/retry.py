"""Retry with exponential backoff + full jitter.

Reference analog: the `retry` util behind JsonRpcHttpClient
(eth1/provider/jsonRpcHttpClient.ts:76 `retryAttempts`/`retryDelay` and
utils/src/retry.ts): a bounded number of re-attempts, a retryable-error
classifier (`shouldRetry`), and a growing delay between attempts. The
delay here is capped exponential with FULL jitter (delay = U(0, cap)),
the AWS-architecture-blog schedule that avoids thundering-herd
re-connects when many nodes lose the same dependency at once.

Everything is injectable: the clock (so tests never sleep), the RNG
(so schedules are reproducible), and the classifier (so JSON-RPC
"server answered with an error" is never retried while transport
failures are).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Callable

from .clock import SYSTEM_CLOCK


class RetryError(Exception):
    """All attempts exhausted; `last` carries the final failure."""

    def __init__(self, attempts: int, last: BaseException):
        super().__init__(
            f"giving up after {attempts} attempts: {last!r}"
        )
        self.attempts = attempts
        self.last = last


def default_retryable(exc: BaseException) -> bool:
    """Transport-shaped failures retry; everything else is a real
    answer (an RPC error object, an auth rejection) and must not."""
    if getattr(exc, "auth_failed", False):
        return False
    retryable = getattr(exc, "retryable", None)
    if retryable is not None:
        return bool(retryable)
    return isinstance(
        exc, (ConnectionError, TimeoutError, asyncio.TimeoutError, OSError)
    )


def backoff_delay(
    attempt: int,
    base_delay: float,
    max_delay: float,
    rng: random.Random | None = None,
    jitter: str = "full",
) -> float:
    """Delay before re-attempt number `attempt` (0-based: the delay
    after the first failure is attempt 0). cap = min(max, base * 2^n);
    full jitter draws U(0, cap], no jitter returns the cap itself."""
    cap = min(max_delay, base_delay * (2.0 ** attempt))
    if jitter == "none":
        return cap
    r = rng.random() if rng is not None else random.random()
    return r * cap


@dataclass
class RetryOptions:
    """Knobs mirroring the reference client's opts (retries = number of
    RE-attempts, so total attempts = retries + 1)."""

    retries: int = 2
    base_delay: float = 0.1
    max_delay: float = 10.0
    jitter: str = "full"  # "full" | "none"
    attempt_timeout: float | None = None  # per-attempt (async only)
    retryable: Callable[[BaseException], bool] = field(
        default=default_retryable
    )
    # on_retry(attempt_index, exc, delay) — metrics/log hook, fired for
    # every failed attempt that will be retried
    on_retry: Callable | None = None


async def retry(fn, opts: RetryOptions | None = None, clock=None,
                rng: random.Random | None = None):
    """Run async `fn()` up to opts.retries + 1 times. Raises the last
    error once attempts are exhausted or the error is non-retryable."""
    opts = opts or RetryOptions()
    clock = clock or SYSTEM_CLOCK
    last: BaseException | None = None
    for attempt in range(opts.retries + 1):
        try:
            if opts.attempt_timeout is not None:
                return await asyncio.wait_for(
                    fn(), timeout=opts.attempt_timeout
                )
            return await fn()
        except BaseException as e:
            last = e
            if attempt >= opts.retries or not opts.retryable(e):
                raise
            delay = backoff_delay(
                attempt, opts.base_delay, opts.max_delay, rng, opts.jitter
            )
            if opts.on_retry is not None:
                opts.on_retry(attempt, e, delay)
            await clock.sleep(delay)
    raise RetryError(opts.retries + 1, last)  # pragma: no cover


def retry_sync(fn, opts: RetryOptions | None = None, clock=None,
               rng: random.Random | None = None):
    """Blocking twin of `retry` for sync call paths (checkpoint sync,
    call_sync); per-attempt timeouts are the callee's responsibility."""
    opts = opts or RetryOptions()
    clock = clock or SYSTEM_CLOCK
    last: BaseException | None = None
    for attempt in range(opts.retries + 1):
        try:
            return fn()
        except BaseException as e:
            last = e
            if attempt >= opts.retries or not opts.retryable(e):
                raise
            delay = backoff_delay(
                attempt, opts.base_delay, opts.max_delay, rng, opts.jitter
            )
            if opts.on_retry is not None:
                opts.on_retry(attempt, e, delay)
            clock.sleep_sync(delay)
    raise RetryError(opts.retries + 1, last)  # pragma: no cover

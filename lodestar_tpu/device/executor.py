"""Node-wide device executor: QoS-classed scheduling, admission
control, and load shedding for every accelerator client.

The chip serves four workloads — BLS verify waves (gossip verdicts),
KZG MSM + device-Fr blob batches, ingest warmup compiles, and
autotune probes — and until this module they contended ad hoc: the
drift monitor had to `hold_intake` the verifier and poll for
quiescence, warmup raced live gossip at node start, and a blob batch
could sit in front of a deadline-critical attestation wave. The
`DeviceExecutor` generalizes the reference's `BlsMultiThreadWorkerPool`
job-queue/priority design (SURVEY §2.3) beyond BLS into three QoS
classes:

  deadline    — gossip attestation/block verdicts. The verifier keeps
                its own depth-N overlapped wave pipeline (verdicts
                stay bit-identical, depth semantics preserved); it
                participates through a PROBE lane — it registers a
                pending-work probe and a quiescence probe, and the
                executor refuses to start bulk/maintenance jobs while
                any deadline probe reports waiting work. Deadline-
                class jobs may also be queued directly (unbounded —
                admission control never sheds deadline).
  bulk        — blob-batch MSM/Fr dispatches, backfill re-verification,
                bench waves. Bounded queue; under overload the
                executor sheds (submit returns None) and the caller
                rides its host fallback tier.
  maintenance — warmup compiles, autotune probes, drift re-tunes.
                Bounded queue, lowest priority, but AGED: bulk can
                never starve maintenance forever (`aging_ms`, or
                `max_bulk_between_maintenance` consecutive bulk jobs,
                whichever trips first).

Scheduling happens at wave boundaries: one worker thread runs one job
at a time, and every pick re-consults the deadline probes — a
deadline job submitted while a bulk batch occupies the pipeline is
dispatched at the next boundary ahead of any further bulk.

The drain primitive replaces the `hold_intake`/`is_quiescent` dance:
`drained()` closes intake for every class (clients' `can_accept_work`
consults the executor, so the processor-fed paths stop feeding),
waits until the executor's own queues are empty AND every registered
quiescence probe reports quiet, then yields. A drift re-tune runs
inside that window with zero calls to `hold_intake`.

`maintenance_checkpoint()` is the yield point for long maintenance
work running OUTSIDE the worker (the warmup thread between compiles,
the tuner between candidate probes): it blocks — bounded — while
deadline work is pending, so node-start warmup no longer competes
with live gossip for the device.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from concurrent.futures import Future

from .health import DeviceTimeout

QOS_DEADLINE = "deadline"
QOS_BULK = "bulk"
QOS_MAINTENANCE = "maintenance"
QOS_CLASSES = (QOS_DEADLINE, QOS_BULK, QOS_MAINTENANCE)

# Admission bounds per class. Deadline is None — unbounded — by
# design: admission control sheds bulk/maintenance under overload,
# never deadline (the verifier's own queue_max bounds that stream at
# ITS intake, where the processor can still count the drop).
DEFAULT_QUEUE_BOUNDS = {
    QOS_DEADLINE: None,
    QOS_BULK: 64,
    QOS_MAINTENANCE: 32,
}

# A maintenance job at the queue head runs no later than this, bulk
# pressure notwithstanding.
DEFAULT_AGING_MS = 2000.0
# ... or after this many consecutive bulk jobs, whichever trips first.
DEFAULT_MAX_BULK_BETWEEN_MAINTENANCE = 16

# How long drained() waits for quiescence before reporting blocked.
DEFAULT_DRAIN_TIMEOUT_S = 10.0


class LatencyHistogram:
    """Fixed-bound latency histogram with host-side quantile
    estimation (linear interpolation inside a bucket). Cheap enough to
    observe per job; the metrics server samples p50/p99 at scrape.
    (Extracted from bls/verifier.py — the verifier re-exports it.)"""

    BOUNDS = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
        0.15, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    )

    def __init__(self):
        self.counts = [0] * (len(self.BOUNDS) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, seconds: float) -> None:
        i = 0
        for i, b in enumerate(self.BOUNDS):
            if seconds <= b:
                break
        else:
            i = len(self.BOUNDS)
        self.counts[i] += 1
        self.count += 1
        self.sum += seconds

    def quantile(self, q: float) -> float:
        """Estimated q-quantile in seconds (0.0 when empty)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= rank and c > 0:
                lo = 0.0 if i == 0 else self.BOUNDS[i - 1]
                hi = (
                    self.BOUNDS[i]
                    if i < len(self.BOUNDS)
                    else self.BOUNDS[-1] * 2
                )
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.BOUNDS[-1] * 2

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_s": (self.sum / self.count) if self.count else 0.0,
            "p50_s": self.quantile(0.5),
            "p99_s": self.quantile(0.99),
        }


class _QueuedJob:
    __slots__ = ("fn", "future", "submitted_at", "deadline_s", "abandoned")

    def __init__(self, fn, future, submitted_at, deadline_s=None):
        self.fn = fn
        self.future = future
        self.submitted_at = submitted_at
        # per-job watchdog deadline override (None = the per-class
        # default from watchdog_deadlines)
        self.deadline_s = deadline_s
        # set by the watchdog when it gives up on this job: the future
        # is already failed with DeviceTimeout and a replacement worker
        # owns the queues — the stuck worker must not touch shared
        # state on its way out (if fn ever returns)
        self.abandoned = False


def _resolve_future(fut: Future, result, exc) -> None:
    """Resolve a future, tolerating a concurrent resolution: the
    watchdog may have already failed it with `DeviceTimeout` by the
    time the worker's fn finally returns (or vice versa). First
    writer wins; the late writer is a no-op instead of an
    InvalidStateError crash in the worker thread."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except Exception:
        pass


class DeviceExecutor:
    """One worker, three bounded intakes, probe-gated priority.

    Thread model: `submit` / `note_shed` / `can_accept_work` /
    `maintenance_checkpoint` are safe from any thread (the warmup
    thread, asyncio executor threads, the event loop). The worker
    thread is the only consumer. Probes run on whichever thread
    consults them and must be cheap and exception-tolerant."""

    def __init__(
        self,
        queue_bounds: dict | None = None,
        aging_ms: float = DEFAULT_AGING_MS,
        max_bulk_between_maintenance: int = (
            DEFAULT_MAX_BULK_BETWEEN_MAINTENANCE
        ),
        drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
        clock=time.monotonic,
        watchdog_deadlines: dict | None = None,
        watchdog_poll_s: float = 0.05,
    ):
        self._clock = clock
        self._bounds = dict(DEFAULT_QUEUE_BOUNDS)
        if queue_bounds:
            for cls, bound in queue_bounds.items():
                if cls not in self._bounds:
                    raise ValueError(
                        f"unknown QoS class {cls!r}; want {QOS_CLASSES}"
                    )
                self._bounds[cls] = bound
        self._aging_s = max(0.0, float(aging_ms)) / 1000.0
        self._max_bulk_between_maintenance = max(
            1, int(max_bulk_between_maintenance)
        )
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: dict[str, deque] = {
            cls: deque() for cls in QOS_CLASSES
        }
        self._running_cls: str | None = None
        self._running_job: _QueuedJob | None = None
        self._running_since = 0.0
        # wave watchdog: per-class deadlines (seconds, None =
        # unbounded). OFF by default — the node arms it with
        # health.default_watchdog_deadlines() (the COVERAGE.md fused
        # stage budget × per-class multiples) on real accelerators;
        # under CPU emulation the budget doesn't hold, so deadlines
        # stay explicit and opt-in.
        self._watchdog_deadlines: dict[str, float | None] = {}
        if watchdog_deadlines:
            for cls, s in watchdog_deadlines.items():
                self._check_cls(cls)
                self._watchdog_deadlines[cls] = s
        self._watchdog_poll_s = max(0.001, float(watchdog_poll_s))
        self._watchdog_thread: threading.Thread | None = None
        self._health = None  # DeviceHealthTracker, via set_health_tracker
        self._intake_closed = 0  # drained() nesting depth
        self._closed = False
        self._deferring = False  # current defer streak (count once)
        self._bulk_since_maintenance = 0
        self._deadline_probes: list = []
        self._quiescence_probes: list = []
        # -- telemetry (bind_executor_collectors samples at scrape) --
        self.sheds: dict[tuple[str, str], int] = {}
        self.completed = {cls: 0 for cls in QOS_CLASSES}
        self.latency = {cls: LatencyHistogram() for cls in QOS_CLASSES}
        self.deadline_deferrals = 0
        self.maintenance_aged = 0
        self.maintenance_yields = 0
        self.drains = 0
        self.drains_blocked = 0
        self.watchdog_trips = {cls: 0 for cls in QOS_CLASSES}
        self.close_timeouts = 0
        # worker generation: the watchdog abandons a hung worker by
        # bumping the generation and spawning a replacement; a stale
        # worker exits the moment it next observes the queues
        self._worker_gen = 0
        self._spawn_worker_locked()
        if any(s is not None for s in self._watchdog_deadlines.values()):
            self._ensure_watchdog_thread_locked()

    # -- client registration -------------------------------------------

    def register_deadline_probe(self, probe) -> None:
        """probe() -> True while the client has deadline work WAITING
        for the device (queued/buffered/rolling, or a wave being
        prepped). While any probe is True the worker defers
        bulk/maintenance picks — the deadline lane owns the next wave
        boundary."""
        with self._lock:
            self._deadline_probes.append(probe)

    def register_quiescence_probe(self, probe) -> None:
        """probe() -> True when the client has NOTHING in flight
        (the verifier's is_quiescent). drained() waits on all of
        these in addition to its own queues."""
        with self._lock:
            self._quiescence_probes.append(probe)

    def set_health_tracker(self, tracker, deadlines=None) -> None:
        """Attach the DeviceHealthTracker: watchdog trips report to it
        (`note_watchdog_trip`), and `deadlines` (per-class seconds,
        e.g. health.default_watchdog_deadlines()) arm the wave
        watchdog when given. deadlines=None leaves the configured
        deadlines untouched — arming stays an explicit decision
        because the fused-budget deadlines only mean something on the
        hardware the budget was measured on."""
        with self._lock:
            self._health = tracker
            if deadlines:
                for cls, s in deadlines.items():
                    self._check_cls(cls)
                    self._watchdog_deadlines[cls] = s
            if any(
                s is not None
                for s in self._watchdog_deadlines.values()
            ):
                self._ensure_watchdog_thread_locked()

    # -- admission ------------------------------------------------------

    def can_accept_work(self, cls: str = QOS_DEADLINE) -> bool:
        """Would a submit of class `cls` be admitted right now?
        Clients gate their intake on this (the verifier ANDs it into
        its own can_accept_work), so a drain closes the processor-fed
        paths without any hold_intake call."""
        self._check_cls(cls)
        with self._lock:
            return self._can_accept_locked(cls)

    def _can_accept_locked(self, cls: str) -> bool:
        if self._closed or self._intake_closed:
            return False
        bound = self._bounds[cls]
        return bound is None or len(self._queues[cls]) < bound

    def submit(self, cls: str, fn, timeout_s: float | None = None) -> Future | None:
        """Queue fn() for the worker; returns a concurrent Future, or
        None when admission control sheds the job (bounded queue full,
        intake drained, or executor closed — counted per class+reason).
        Shed callers fall back to their host tier; they never block.
        timeout_s overrides the per-class watchdog deadline for this
        one job (health probes pass their own explicit timeout since
        the maintenance class is otherwise unbounded)."""
        self._check_cls(cls)
        with self._cond:
            if self._closed:
                self._shed_locked(cls, "closed")
                return None
            if self._intake_closed:
                self._shed_locked(cls, "drain")
                return None
            bound = self._bounds[cls]
            if bound is not None and len(self._queues[cls]) >= bound:
                self._shed_locked(cls, "queue_full")
                return None
            fut: Future = Future()
            self._queues[cls].append(
                _QueuedJob(fn, fut, self._clock(), deadline_s=timeout_s)
            )
            self._cond.notify_all()
            if timeout_s is not None:
                self._ensure_watchdog_thread_locked()
            return fut

    def note_shed(self, cls: str, reason: str) -> None:
        """External shed accounting: a client refused work at ITS
        intake because the device path was saturated (the processor's
        can_accept_work rejection sites). Keeps every drop visible on
        one series (lodestar_device_sheds_total) whether the executor
        or the client's own bound did the refusing."""
        self._check_cls(cls)
        with self._lock:
            self._shed_locked(cls, reason)

    def _shed_locked(self, cls: str, reason: str) -> None:
        key = (cls, reason)
        self.sheds[key] = self.sheds.get(key, 0) + 1

    def _check_cls(self, cls: str) -> None:
        if cls not in QOS_CLASSES:
            raise ValueError(
                f"unknown QoS class {cls!r}; want {QOS_CLASSES}"
            )

    # -- introspection --------------------------------------------------

    def queue_depths(self) -> dict[str, int]:
        with self._lock:
            return {c: len(q) for c, q in self._queues.items()}

    def shed_counts(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self.sheds)

    def intake_open(self) -> bool:
        with self._lock:
            return not self._closed and not self._intake_closed

    # -- deadline lane --------------------------------------------------

    def _deadline_pending_locked(self) -> bool:
        if self._queues[QOS_DEADLINE]:
            return True
        for probe in self._deadline_probes:
            try:
                if probe():
                    return True
            except Exception:
                # a broken probe must not stall bulk forever
                continue
        return False

    def maintenance_checkpoint(self, timeout_s: float = 2.0) -> bool:
        """Yield point for long maintenance work running OUTSIDE the
        worker (the warmup thread between compiles, the tuner between
        candidate probes). Blocks — bounded — while deadline work is
        pending, so a compile storm never sits in front of a live
        gossip wave. Returns True when it actually yielded."""
        deadline = self._clock() + max(0.0, timeout_s)
        yielded = False
        with self._cond:
            while (
                not self._closed
                and self._deadline_pending_locked()
                and self._clock() < deadline
            ):
                if not yielded:
                    yielded = True
                    self.maintenance_yields += 1
                self._cond.wait(timeout=0.005)
        return yielded

    # -- drain (the hold_intake replacement) ----------------------------

    @contextlib.contextmanager
    def drained(self, timeout_s: float | None = None):
        """Close intake for EVERY class, wait for device quiet, yield
        whether quiet was reached. The drift monitor wraps a re-tune:

            with executor.drained() as quiet:
                if not quiet:        # still busy at timeout: defer,
                    ...              # count retunes_blocked, retry
                tuner.tune(...)      # device is quiet AND stays fed
                                     # by nothing for the duration

        Intake reopens on exit either way. While closed, every
        client's can_accept_work reports False through the executor
        consult — semantically the old hold_intake, for all classes
        at once, with sheds counted instead of silent."""
        if timeout_s is None:
            timeout_s = self.drain_timeout_s
        with self._cond:
            self._intake_closed += 1
        try:
            quiet = self._await_quiet(timeout_s)
            with self._lock:
                if quiet:
                    self.drains += 1
                else:
                    self.drains_blocked += 1
            yield quiet
        finally:
            with self._cond:
                self._intake_closed -= 1
                self._cond.notify_all()

    def _await_quiet(self, timeout_s: float) -> bool:
        deadline = self._clock() + max(0.0, timeout_s)
        with self._cond:
            while self._clock() <= deadline:
                if self._quiet_locked():
                    return True
                self._cond.wait(timeout=0.01)
            return self._quiet_locked()

    def _quiet_locked(self) -> bool:
        if self._running_cls is not None:
            return False
        if any(self._queues[c] for c in QOS_CLASSES):
            return False
        if self._deadline_pending_locked():
            return False
        for probe in self._quiescence_probes:
            try:
                if not probe():
                    return False
            except Exception:
                # a broken probe must not wedge every future drain
                continue
        return True

    # -- worker ---------------------------------------------------------

    def _next_job_locked(self):
        """One wave-boundary scheduling decision. Returns
        (cls, job) or None (nothing runnable right now)."""
        dq = self._queues[QOS_DEADLINE]
        if dq:
            self._deferring = False
            return QOS_DEADLINE, dq.popleft()
        bq = self._queues[QOS_BULK]
        mq = self._queues[QOS_MAINTENANCE]
        if not bq and not mq:
            return None
        if self._deadline_pending_locked():
            # a deadline client owns the next wave boundary; count
            # the defer streak once, not per 5ms poll
            if not self._deferring:
                self._deferring = True
                self.deadline_deferrals += 1
            return None
        self._deferring = False
        if mq:
            waited = self._clock() - mq[0].submitted_at
            if (
                not bq
                or waited >= self._aging_s
                or self._bulk_since_maintenance
                >= self._max_bulk_between_maintenance
            ):
                if bq:
                    self.maintenance_aged += 1
                self._bulk_since_maintenance = 0
                return QOS_MAINTENANCE, mq.popleft()
        if bq:
            self._bulk_since_maintenance += 1
            return QOS_BULK, bq.popleft()
        return None

    def _spawn_worker_locked(self) -> None:
        gen = self._worker_gen
        self._worker = threading.Thread(
            target=self._run,
            args=(gen,),
            # replacement workers carry the generation; clients key
            # on the base name (the KZG bulk-lane test does)
            name=(
                "device-executor"
                if gen == 0
                else f"device-executor-r{gen}"
            ),
            daemon=True,
        )
        self._worker.start()

    def _run(self, gen: int) -> None:
        while True:
            with self._cond:
                picked = None
                while picked is None:
                    if gen != self._worker_gen:
                        return  # abandoned: a replacement owns the queues
                    if self._closed:
                        self._reject_queued_locked()
                        return
                    picked = self._next_job_locked()
                    if picked is None:
                        # empty queues can sleep long (submit
                        # notifies); a probe-deferred pick re-polls
                        # fast — the probes have no notify hook
                        idle = not any(
                            self._queues[c] for c in QOS_CLASSES
                        )
                        self._cond.wait(
                            timeout=0.25 if idle else 0.005
                        )
                cls, job = picked
                self._running_cls = cls
                self._running_job = job
                self._running_since = self._clock()
            ran = False
            res = exc = None
            try:
                if job.future.set_running_or_notify_cancel():
                    ran = True
                    try:
                        res = job.fn()
                    except BaseException as e:
                        exc = e
            finally:
                with self._cond:
                    if not job.abandoned:
                        self._running_cls = None
                        self._running_job = None
                        self.completed[cls] += 1
                        self.latency[cls].observe(
                            self._clock() - job.submitted_at
                        )
                        self._cond.notify_all()
            if ran:
                # outside the lock; a no-op if the watchdog already
                # failed this future with DeviceTimeout
                _resolve_future(job.future, res, exc)
            if job.abandoned:
                # this thread was given up on while fn was stuck; the
                # replacement worker owns _running_* and the queues
                return

    # -- wave watchdog --------------------------------------------------

    def _ensure_watchdog_thread_locked(self) -> None:
        if self._watchdog_thread is not None or self._closed:
            return
        t = threading.Thread(
            target=self._watchdog_loop,
            name="device-executor-watchdog",
            daemon=True,
        )
        self._watchdog_thread = t
        t.start()

    def _watchdog_loop(self) -> None:
        # real sleep for pacing, but all deadline math goes through
        # self._clock so tests drive watchdog_check() with ManualClock
        while not self._closed:
            time.sleep(self._watchdog_poll_s)
            try:
                self.watchdog_check()
            except Exception:
                continue

    def _effective_deadline_locked(self, job, cls) -> float | None:
        if job.deadline_s is not None:
            return job.deadline_s
        return self._watchdog_deadlines.get(cls)

    def watchdog_check(self) -> list[str]:
        """One watchdog pass: if the running job has overrun its
        per-class deadline, fail its future with `DeviceTimeout`, mark
        it abandoned, bump the worker generation, and spawn a
        replacement worker — the queue keeps moving while the stuck
        thread blocks on the device call forever. Reports the trip to
        the attached health tracker. Public so tests (and the
        scenario fabric) can drive it with a ManualClock instead of
        waiting out the poll loop. Returns the classes tripped."""
        tripped = []
        with self._cond:
            job = self._running_job
            cls = self._running_cls
            if job is not None and cls is not None and not job.abandoned:
                deadline = self._effective_deadline_locked(job, cls)
                if deadline is not None:
                    elapsed = self._clock() - self._running_since
                    if elapsed > deadline:
                        job.abandoned = True
                        self.watchdog_trips[cls] = (
                            self.watchdog_trips.get(cls, 0) + 1
                        )
                        self._running_job = None
                        self._running_cls = None
                        self._worker_gen += 1
                        self._spawn_worker_locked()
                        self._cond.notify_all()
                        tripped.append((cls, job, elapsed, deadline))
            health = self._health
        for cls, job, elapsed, deadline in tripped:
            _resolve_future(
                job.future,
                None,
                DeviceTimeout(
                    f"{cls} dispatch overran its watchdog deadline "
                    f"({elapsed:.3f}s > {deadline:.3f}s)"
                ),
            )
            if health is not None:
                try:
                    health.note_watchdog_trip(cls)
                except Exception:
                    pass
        return [cls for cls, *_ in tripped]

    def _reject_queued_locked(self) -> None:
        for cls in QOS_CLASSES:
            q = self._queues[cls]
            while q:
                job = q.popleft()
                self._shed_locked(cls, "closed")
                job.future.cancel()
        self._cond.notify_all()

    # -- lifecycle ------------------------------------------------------

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop admitting, let the running job finish, cancel queued
        futures (counted as sheds, reason='closed'), stop the worker.
        Idempotent.

        A PERMANENTLY HUNG running job cannot hold close hostage: the
        join is bounded by timeout_s, after which the hang is counted
        (`close_timeouts`, exported as
        lodestar_device_executor_close_timeouts_total) and the queued
        futures are cancelled HERE — the hung worker is blocked
        inside job.fn() and may never reach its own
        _reject_queued_locked, so waiting on it would leak every
        queued future as forever-pending. The running job itself is
        NOT failed by close: a merely-slow job still resolves its
        future when fn returns (and the worker then exits on the
        closed flag); a truly hung job's future is the wave
        watchdog's to fail with `DeviceTimeout` when deadlines are
        armed. The left-behind thread is a daemon; it dies with the
        process."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
            worker = self._worker
        worker.join(timeout=timeout_s)
        if not worker.is_alive():
            return
        with self._cond:
            self.close_timeouts += 1
            self._reject_queued_locked()

    @property
    def closed(self) -> bool:
        return self._closed


# ---------------------------------------------------------------------------
# /metrics bridging (the addCollect pattern every service uses)
# ---------------------------------------------------------------------------


def bind_executor_collectors(metrics, executor: DeviceExecutor) -> None:
    """Wire the m.device_executor registry namespace
    (metrics/beacon.py) to sample the executor at scrape time."""

    def _sheds(g):
        for (cls, reason), n in executor.shed_counts().items():
            g.set(n, cls=cls, reason=reason)

    metrics.sheds_total.add_collect(_sheds)
    metrics.queue_depth.add_collect(
        lambda g: [
            g.set(n, cls=c)
            for c, n in executor.queue_depths().items()
        ]
    )
    metrics.completed_total.add_collect(
        lambda g: [
            g.set(n, cls=c) for c, n in executor.completed.items()
        ]
    )
    metrics.latency_p50.add_collect(
        lambda g: [
            g.set(h.quantile(0.5), cls=c)
            for c, h in executor.latency.items()
        ]
    )
    metrics.latency_p99.add_collect(
        lambda g: [
            g.set(h.quantile(0.99), cls=c)
            for c, h in executor.latency.items()
        ]
    )
    metrics.deadline_deferrals_total.add_collect(
        lambda g: g.set(executor.deadline_deferrals)
    )
    metrics.maintenance_aged_total.add_collect(
        lambda g: g.set(executor.maintenance_aged)
    )
    metrics.maintenance_yields_total.add_collect(
        lambda g: g.set(executor.maintenance_yields)
    )
    metrics.drains_total.add_collect(lambda g: g.set(executor.drains))
    metrics.drains_blocked_total.add_collect(
        lambda g: g.set(executor.drains_blocked)
    )
    metrics.intake_open.add_collect(
        lambda g: g.set(1.0 if executor.intake_open() else 0.0)
    )
    metrics.close_timeouts_total.add_collect(
        lambda g: g.set(executor.close_timeouts)
    )

"""Device self-management: the feedback loop from telemetry to knobs,
and the scheduler that owns the accelerator.

`metrics/device.py` made the JAX/XLA execution layer observable;
this package closes the loop — `autotune.py` turns the observed
numbers back into the live configuration knobs (limb backend, ingest
gate, bucket-ladder top, verifier latency budget) so one binary
converges to its host's optimum without operator tuning — and
`executor.py` arbitrates the device itself: every accelerator client
(gossip verdicts, KZG blob batches, warmup/auto-tune compiles) goes
through one QoS-classed executor with admission control, load
shedding, and drain-for-retune.
"""

from .autotune import (  # noqa: F401
    DeviceAutotuner,
    DriftMonitor,
    TunedConfig,
    apply_decision,
    applied_decision,
    budget_shares,
    current_config,
    load_decision,
    parse_grid,
    provenance_fields,
    select_config,
)
from .executor import (  # noqa: F401
    QOS_BULK,
    QOS_CLASSES,
    QOS_DEADLINE,
    QOS_MAINTENANCE,
    DeviceExecutor,
    bind_executor_collectors,
)
from .health import (  # noqa: F401
    DeviceHealthTracker,
    DeviceTimeout,
    HealthState,
    bind_health_collectors,
    classify_device_error,
    default_watchdog_deadlines,
    make_device_probe,
)

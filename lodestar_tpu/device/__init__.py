"""Device self-management: the feedback loop from telemetry to knobs.

`metrics/device.py` made the JAX/XLA execution layer observable;
this package closes the loop — `autotune.py` turns the observed
numbers back into the live configuration knobs (limb backend, ingest
gate, bucket-ladder top, verifier latency budget) so one binary
converges to its host's optimum without operator tuning.
"""

from .autotune import (  # noqa: F401
    DeviceAutotuner,
    DriftMonitor,
    TunedConfig,
    apply_decision,
    applied_decision,
    budget_shares,
    current_config,
    load_decision,
    parse_grid,
    provenance_fields,
    select_config,
)

"""Device fault domain: health state machine, wave watchdog
deadlines, and the JAX device-error taxonomy.

Until this module the accelerator was an ASSUMED-HEALTHY component: a
hung device program blocked the executor worker forever
(device/executor.py ran job.fn() with no deadline), and device errors
were swallowed by bare ``except Exception`` fallbacks with zero
classification (crypto/kzg.py, bls/verifier.py) — a sick or preempted
TPU degraded the node invisibly and was retried on every single call.
This module makes the device a failure-isolated dependency behind the
same contract the engine API already has (resilience/breaker.py):

  ONLINE ──fault──▶ DEGRADED ──faults──▶ QUARANTINED ──backoff──▶
  PROBING ──N successes──▶ ONLINE (warmup re-kicked)
                  ╰──probe failure──▶ QUARANTINED (backoff doubles)

* `DeviceHealthTracker` — the state machine, composed over the
  half-open `CircuitBreaker` with injectable clocks (closed=ONLINE/
  DEGRADED, open=QUARANTINED, half_open=PROBING). Every device client
  reports faults through `record_fault` and consults
  `device_allowed()` before dispatching.

* Error taxonomy (`classify_device_error`) — XlaRuntimeError
  RESOURCE_EXHAUSTED is an OOM: shrink the bucket ladder's top rung
  before quarantining (a smaller footprint often fits). A compile
  failure quarantines only that stage program (the registry keeps the
  rest of the pipeline live). Device-lost / INTERNAL / watchdog
  timeouts count toward the breaker — enough of them quarantine the
  whole device. Programming errors (TypeError/KeyError from our own
  code) are NOT device faults: call sites must re-raise them instead
  of letting them masquerade as hardware flakiness.

* Wave watchdog deadlines — per-QoS-class deadlines derived from
  COVERAGE.md's fused stage budget (autotune.STAGE_BUDGET_MS: prepare
  288.0 + pairing 78.4 + final 16.2 ≈ 382.6 ms for the 2048-set
  production bucket). The executor's watchdog thread marks overruns,
  fails the job's future with `DeviceTimeout`, and trips this tracker
  without wedging the worker (device/executor.py spawns a replacement
  worker and abandons the stuck one).

* Node-wide failover — on quarantine every client rides its host
  tier: the BLS verifier routes buckets to the host oracle (verdicts
  bit-identical — per-set exact pairing checks), KZG MSM/Fr ride
  their existing host tiers, warmup suspends (bls/kernels health
  gate), the autotuner suspends and the drift monitor defers (the
  frozen-config invariant the scenario fleet proves for incidents).
  `note_failover(client)` counts every failed-over dispatch and
  answers whether this client should LOG the transition (once per
  state change, not per call).

* PROBING reinstates live — `maybe_probe` runs a maintenance-class
  known-answer dispatch at the smallest warm rung once the breaker's
  backoff elapses; `probe_successes` consecutive successes reopen the
  device path and re-kick warmup (`warmup_kick`), one failure re-trips
  with the backoff doubled (bounded by `max_backoff_s`).

Grounding: 2G2T (PAPERS.md, arXiv 2602.23464) argues an outsourced
verifier must never be silently trusted — the failover keeps verdict
obligations on the bit-exact host tiers; the committee signature-load
model (arXiv 2302.00418) is why gossip verdicts keep their deadline
obligations through the incident instead of erroring out.
"""

from __future__ import annotations

import threading
from enum import Enum

from ..resilience.breaker import BreakerState, CircuitBreaker
from ..resilience.clock import SYSTEM_CLOCK
from .autotune import STAGE_BUDGET_MS


class DeviceTimeout(RuntimeError):
    """A device dispatch overran its watchdog deadline. The job's
    future fails with this; the worker that ran it is abandoned (the
    underlying device call may never return) and replaced."""


class HealthState(str, Enum):
    online = "online"
    degraded = "degraded"
    quarantined = "quarantined"
    probing = "probing"


# stable gauge encoding (lodestar_device_health_state)
HEALTH_STATE_INDEX = {
    HealthState.online: 0,
    HealthState.degraded: 1,
    HealthState.quarantined: 2,
    HealthState.probing: 3,
}


# ---------------------------------------------------------------------------
# Watchdog deadlines (COVERAGE.md fused stage budget -> per-class)
# ---------------------------------------------------------------------------

# The fused three-program budget for the 2048-set production bucket
# (COVERAGE.md "Device stage budget", re-exported by autotune):
# prepare 288.0 + pairing 78.4 + final 16.2 ms.
FUSED_BUDGET_MS = sum(STAGE_BUDGET_MS.values())

# Per-class multiples of the fused budget. These are HANG detectors,
# not latency SLOs: a healthy wave finishes in ~1 budget; prep jitter,
# queueing, and retry ladders legitimately stack a few more, so the
# deadline class trips only past 8x (~3.1 s) and bulk (blob batches,
# host-prep-heavy) past 16x (~6.1 s). Maintenance is None — warmup /
# autotune compiles legitimately run minutes cold; probes pass their
# own explicit per-job timeout instead.
WATCHDOG_BUDGET_MULTIPLES = {
    "deadline": 8.0,
    "bulk": 16.0,
    "maintenance": None,
}


def watchdog_deadline_s(cls: str) -> float | None:
    """The watchdog deadline for one QoS class, in seconds (None =
    unbounded; see WATCHDOG_BUDGET_MULTIPLES)."""
    scale = WATCHDOG_BUDGET_MULTIPLES.get(cls)
    if scale is None:
        return None
    return FUSED_BUDGET_MS * scale / 1000.0


def default_watchdog_deadlines() -> dict[str, float | None]:
    """Per-class watchdog deadlines for DeviceExecutor wiring."""
    return {
        cls: watchdog_deadline_s(cls)
        for cls in WATCHDOG_BUDGET_MULTIPLES
    }


# ---------------------------------------------------------------------------
# Error taxonomy
# ---------------------------------------------------------------------------

# fault kinds record_fault understands
FAULT_KINDS = (
    "oom", "compile", "device_lost", "timeout", "unknown",
)

# exception types that are OUR bugs, never the device's. A TypeError
# out of a dispatch lambda means the code is wrong; counting it as a
# device fault would quarantine healthy hardware and hide the bug.
_PROGRAMMING_ERRORS = (
    TypeError,
    KeyError,
    AttributeError,
    NameError,
    IndexError,
    AssertionError,
)

_OOM_MARKERS = ("resource_exhausted", "out of memory", "oom")
_COMPILE_MARKERS = ("compilation", "compile", "xla_compile")
_DEVICE_LOST_MARKERS = (
    "device lost", "device_lost", "internal:", "internal error",
    "data_loss", "aborted", "unavailable", "failed_precondition",
    "deadline_exceeded", "halted", "preempted",
)


def classify_device_error(exc: BaseException) -> str:
    """Map an exception from a device dispatch onto the taxonomy:
    'oom' | 'compile' | 'device_lost' | 'timeout' | 'programming' |
    'unknown'. Matches the XlaRuntimeError type by NAME (jaxlib moves
    it between modules across versions) and falls back to status-code
    markers in the message, so injected faults (sim/faults.py) and
    real chips classify identically."""
    if isinstance(exc, DeviceTimeout):
        return "timeout"
    if isinstance(exc, _PROGRAMMING_ERRORS):
        return "programming"
    names = {t.__name__ for t in type(exc).__mro__}
    msg = str(exc).lower()
    is_xla = "XlaRuntimeError" in names or "JaxRuntimeError" in names
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if any(m in msg for m in _COMPILE_MARKERS):
        return "compile"
    if any(m in msg for m in _DEVICE_LOST_MARKERS):
        return "device_lost"
    if is_xla:
        # an XLA error we can't bucket finer still indicts the device
        return "device_lost"
    return "unknown"


# ---------------------------------------------------------------------------
# The tracker
# ---------------------------------------------------------------------------


class DeviceHealthTracker:
    """ONLINE → DEGRADED → QUARANTINED → PROBING on a CircuitBreaker.

    Thread model: faults arrive from executor/prep/asyncio threads;
    everything mutating holds one re-entrant lock. Callbacks
    (`on_transition`, `warmup_kick`, `ladder_shrink`) run outside any
    caller-visible invariant but inside the lock — keep them cheap
    and non-reentrant.

    clock: injectable (resilience/clock.py ManualClock in tests).
    failure_threshold: consecutive breaker-counted faults that open
      the breaker (quarantine the device).
    quarantine_reset_s: base backoff before the first probe; doubles
      on every failed probe round up to `max_backoff_s`, resets on
      reinstatement.
    probe_successes: consecutive known-answer probe successes that
      reopen the device path.
    ladder_shrink: () -> bool — shrink the bucket ladder/top rung on
      OOM; True = shrunk (the OOM is absorbed as DEGRADED), False =
      nothing left to shrink (the OOM counts toward quarantine).
      Default: `default_ladder_shrink` (bls/kernels.set_ladder_top to
      the next rung down).
    warmup_kick: () -> None — re-kick warmup on reinstatement (the
      node wires verifier.start_warmup).
    """

    def __init__(
        self,
        name: str = "device",
        clock=None,
        failure_threshold: int = 3,
        quarantine_reset_s: float = 10.0,
        max_backoff_s: float = 300.0,
        probe_successes: int = 3,
        ladder_shrink=None,
        warmup_kick=None,
        on_transition=None,  # fn(old: HealthState, new: HealthState)
        logger=None,
    ):
        self.name = name
        self.clock = clock or SYSTEM_CLOCK
        self._base_reset_s = float(quarantine_reset_s)
        self.max_backoff_s = float(max_backoff_s)
        self.probe_successes = max(1, int(probe_successes))
        self._ladder_shrink = (
            ladder_shrink
            if ladder_shrink is not None
            else default_ladder_shrink
        )
        self._warmup_kick = warmup_kick
        self._on_transition = on_transition
        if logger is None:
            from ..logger import get_logger

            logger = get_logger("device-health")
        self.log = logger
        self._lock = threading.RLock()
        self.breaker = CircuitBreaker(
            name=name,
            failure_threshold=max(1, int(failure_threshold)),
            reset_timeout=self._base_reset_s,
            half_open_max=1,
            clock=self.clock,
            on_transition=self._breaker_moved,
        )
        self._degraded = False
        self._probe_fn = None
        self._probe_streak = 0
        # epoch bumps on EVERY state transition — the log-once-per-
        # transition key clients consult through should_log()
        self.epoch = 0
        self._logged: dict[str, int] = {}
        # -- telemetry (bind_health_collectors samples at scrape) ----
        self.faults: dict[str, int] = {}
        self.watchdog_trips: dict[str, int] = {}
        self.failover_dispatches: dict[str, int] = {}
        self.probes = {"success": 0, "failure": 0}
        self.quarantines = 0
        self.reinstatements = 0
        self.oom_shrinks = 0
        self.quarantined_programs: set[str] = set()
        # full audit trail of (time, old, new) — scenarios assert it
        self.transitions: list[tuple[float, HealthState, HealthState]] = []

    # -- state ----------------------------------------------------------

    @property
    def state(self) -> HealthState:
        """The current state. Pure read — the open→probing move
        happens in maybe_probe (via breaker.allows), never here."""
        s = self.breaker.state
        if s is BreakerState.open:
            return HealthState.quarantined
        if s is BreakerState.half_open:
            return HealthState.probing
        return (
            HealthState.degraded
            if self._degraded
            else HealthState.online
        )

    def state_index(self) -> int:
        return HEALTH_STATE_INDEX[self.state]

    def device_allowed(self) -> bool:
        """May a client dispatch to the device right now? False while
        QUARANTINED or PROBING — during probing only the probe itself
        touches the chip (a live wave racing the probe would make the
        known-answer check unreadable)."""
        return self.breaker.state is BreakerState.closed

    def program_quarantined(self, program: str) -> bool:
        """Is ONE stage program quarantined (compile-failure
        isolation) while the rest of the device stays live?"""
        with self._lock:
            return program in self.quarantined_programs

    # -- fault intake ---------------------------------------------------

    def record_fault(
        self,
        kind_or_exc,
        client: str = "unknown",
        program: str | None = None,
    ) -> str:
        """Report one device fault; returns the taxonomy kind.
        Accepts a kind string or the exception itself. Programming
        errors are REJECTED (ValueError) — the call site must re-raise
        them, not feed them here."""
        if isinstance(kind_or_exc, BaseException):
            kind = classify_device_error(kind_or_exc)
        else:
            kind = str(kind_or_exc)
        if kind == "programming":
            raise ValueError(
                "programming errors are not device faults; re-raise "
                "them at the call site"
            )
        if kind not in FAULT_KINDS:
            kind = "unknown"
        with self._lock:
            self.faults[kind] = self.faults.get(kind, 0) + 1
            if kind == "oom":
                self._on_oom(client)
            elif kind == "compile":
                # quarantine only the failing stage program; the rest
                # of the pipeline keeps the device
                self.quarantined_programs.add(program or client)
                if not self._degraded:
                    self._degraded = True
                    self._bump_epoch(
                        HealthState.online, HealthState.degraded
                    )
            else:
                # timeout / device_lost / unknown indict the device
                self.breaker.on_failure()
        return kind

    def _on_oom(self, client: str) -> None:
        """RESOURCE_EXHAUSTED: shrink the bucket ladder before
        quarantining — a smaller top rung often fits the remaining
        HBM (preemption neighbors, fragmentation)."""
        shrunk = False
        try:
            shrunk = bool(self._ladder_shrink())
        except Exception as e:
            self.log.warn(
                "ladder shrink failed on device OOM",
                {"client": client, "err": repr(e)},
            )
        if shrunk:
            self.oom_shrinks += 1
            if not self._degraded:
                self._degraded = True
                self._bump_epoch(
                    HealthState.online, HealthState.degraded
                )
        else:
            # nothing left to shrink: the OOM counts like any other
            # device fault and can open the breaker
            self.breaker.on_failure()

    def note_watchdog_trip(self, cls: str) -> None:
        """A wave watchdog overrun in QoS class `cls` (the executor's
        watchdog thread, or the verifier's wave deadline for the
        deadline class). Counts per class and feeds the breaker as a
        'timeout' fault."""
        with self._lock:
            self.watchdog_trips[cls] = (
                self.watchdog_trips.get(cls, 0) + 1
            )
        self.record_fault("timeout", client=f"watchdog:{cls}")

    def record_success(self) -> None:
        """A live device dispatch completed while the path was open —
        resets the consecutive-failure count (flaky devices only
        quarantine on CONSECUTIVE faults, the breaker contract)."""
        with self._lock:
            if self.breaker.state is BreakerState.closed:
                self.breaker.consecutive_failures = 0

    # -- failover accounting -------------------------------------------

    def note_failover(self, client: str) -> bool:
        """One dispatch served by a host tier because the device path
        is closed. Returns True when this client should LOG the event
        (once per state transition, not per call — a quarantined node
        sees thousands of failovers per second)."""
        with self._lock:
            self.failover_dispatches[client] = (
                self.failover_dispatches.get(client, 0) + 1
            )
            return self._should_log_locked(client)

    def should_log(self, client: str) -> bool:
        """Log-once-per-transition gate for clients that classify and
        fall back without counting a failover dispatch."""
        with self._lock:
            return self._should_log_locked(client)

    def _should_log_locked(self, client: str) -> bool:
        if self._logged.get(client) == self.epoch:
            return False
        self._logged[client] = self.epoch
        return True

    # -- probing / reinstatement ---------------------------------------

    def set_probe(self, fn) -> None:
        """Install the known-answer probe: () -> bool (True = the
        device answered the smallest warm rung correctly). The node
        wires a maintenance-class executor dispatch with an explicit
        per-job timeout."""
        self._probe_fn = fn

    def maybe_probe(self, probe_fn=None):
        """Drive reinstatement: when QUARANTINED and the backoff has
        elapsed, run one probe (open→PROBING via the breaker's
        half-open gate). `probe_successes` consecutive successes
        reopen the device path (warmup re-kicked); one failure
        re-trips QUARANTINED with the backoff doubled. Returns the
        probe outcome (bool) or None when no probe ran."""
        fn = probe_fn or self._probe_fn
        if fn is None:
            return None
        with self._lock:
            if self.breaker.state is BreakerState.closed:
                return None
            if not self.breaker.allows():
                return None  # backoff not elapsed / probe budget used
        try:
            ok = bool(fn())
        except Exception:
            ok = False
        with self._lock:
            if ok:
                self.probes["success"] += 1
                self._probe_streak += 1
                if self._probe_streak >= self.probe_successes:
                    self.breaker.on_success()  # -> closed: reinstated
                else:
                    # hand the probe slot back so the NEXT maybe_probe
                    # is allowed without waiting out another backoff
                    self.breaker.release_probe()
            else:
                self.probes["failure"] += 1
                self._probe_streak = 0
                self.breaker.reset_timeout = min(
                    self.max_backoff_s, self.breaker.reset_timeout * 2
                )
                self.breaker.on_failure()  # half_open -> open
        return ok

    # -- transitions ----------------------------------------------------

    def _breaker_moved(self, name, old: BreakerState, new) -> None:
        """Breaker transition -> health transition + side effects.
        Runs under the tracker lock for every path that mutates the
        breaker through this tracker."""
        before = _STATE_OF_BREAKER[old]
        if before is None:  # closed: online/degraded split
            before = (
                HealthState.degraded
                if self._degraded
                else HealthState.online
            )
        after = _STATE_OF_BREAKER[new]
        if after is None:
            after = HealthState.online  # reinstatement clears degraded
        if new is BreakerState.open:
            self.quarantines += 1
            self._probe_streak = 0
        if new is BreakerState.closed:
            # reinstated: clear degradation marks, restore the base
            # backoff, re-kick warmup for whatever went cold
            self.reinstatements += 1
            self._degraded = False
            self.quarantined_programs.clear()
            self.breaker.reset_timeout = self._base_reset_s
        self._bump_epoch(before, after)
        if new is BreakerState.closed and self._warmup_kick is not None:
            try:
                self._warmup_kick()
            except Exception as e:
                self.log.warn(
                    "warmup re-kick failed after reinstatement",
                    {"err": repr(e)},
                )

    def _bump_epoch(self, old: HealthState, new: HealthState) -> None:
        self.epoch += 1
        self.transitions.append((self.clock.monotonic(), old, new))
        self.log.info(
            "device health transition",
            {"from": old.value, "to": new.value, "epoch": self.epoch},
        )
        if self._on_transition is not None:
            try:
                self._on_transition(old, new)
            except Exception:
                pass

    # -- introspection --------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state.value,
                "epoch": self.epoch,
                "faults": dict(self.faults),
                "watchdog_trips": dict(self.watchdog_trips),
                "failover_dispatches": dict(self.failover_dispatches),
                "probes": dict(self.probes),
                "quarantines": self.quarantines,
                "reinstatements": self.reinstatements,
                "oom_shrinks": self.oom_shrinks,
                "quarantined_programs": sorted(
                    self.quarantined_programs
                ),
            }


# breaker state -> health state (None = closed, resolved against the
# degraded flag at transition time)
_STATE_OF_BREAKER = {
    BreakerState.closed: None,
    BreakerState.open: HealthState.quarantined,
    BreakerState.half_open: HealthState.probing,
}


# ---------------------------------------------------------------------------
# Default wiring helpers
# ---------------------------------------------------------------------------


def default_ladder_shrink() -> bool:
    """Shrink the bucket ladder's top rung one step down the
    selectable tops (2048 -> 1024 -> 512). Returns True when a shrink
    happened; False when the top is already at the floor (the OOM
    then counts toward quarantine). rewarm=False: the device just
    OOMed — a background compile storm is the last thing it needs;
    reinstatement re-kicks warmup."""
    from ..bls import kernels

    top = kernels.ladder_top()
    floor = kernels._MID_RUNGS[-1]
    if top <= floor:
        return False
    # the live BUCKET_LADDER only carries the CURRENT top above the
    # mid rungs; the shrink steps through the selectable tops
    rungs = sorted(set(kernels.LADDER_TOPS) | {floor})
    lower = [b for b in rungs if b < top]
    if not lower:
        return False
    kernels.set_ladder_top(max(lower), rewarm=False)
    return True


def make_device_probe(executor=None, bucket: int = 4,
                      timeout_s: float = 30.0):
    """Build the known-answer probe: one real staged verify at the
    smallest rung (valid synthetic sets — the answer is True by
    construction), dispatched maintenance-class through the executor
    when one is wired (with an explicit per-job watchdog deadline so
    a still-hung device fails the probe instead of wedging it)."""

    def probe() -> bool:
        def dispatch() -> bool:
            import jax.numpy as jnp

            from ..bls import kernels
            from ..crypto.bls import curve as oc
            from ..ops import curve as C

            n = bucket
            hs = [oc.g2_mul(oc.G2_GEN, 11 + i) for i in range(n)]
            pks, sigs = [], []
            for i, h in enumerate(hs):
                sk = 17 + i
                pks.append(oc.g1_mul(oc.G1_GEN, sk))
                sigs.append(oc.g2_mul(h, sk))
            pk_dev = C.g1_batch_from_ints(pks)
            h_pt = C.g2_batch_from_ints(hs)
            sig_dev = C.g2_batch_from_ints(sigs)
            bits = C.scalars_to_bits(
                [(0x51D5 + 2 * i) | 1 for i in range(n)],
                kernels.RAND_BITS,
            )
            mask = jnp.ones(n, bool)
            return bool(
                kernels.run_verify_batch_async(
                    pk_dev, (h_pt.x, h_pt.y), sig_dev, bits, mask
                )
            )

        if executor is None:
            return dispatch()
        fut = executor.submit(
            "maintenance", dispatch, timeout_s=timeout_s
        )
        if fut is None:
            return False  # shed/closed: the device never answered
        return bool(fut.result(timeout=timeout_s * 2))

    return probe


# ---------------------------------------------------------------------------
# /metrics bridging (the addCollect pattern every service uses)
# ---------------------------------------------------------------------------


def bind_health_collectors(
    metrics, tracker: DeviceHealthTracker
) -> None:
    """Wire the m.device_health registry namespace
    (metrics/beacon.py) to sample the tracker at scrape time."""
    metrics.state.add_collect(
        lambda g: g.set(tracker.state_index())
    )

    def _trips(g):
        for cls, n in dict(tracker.watchdog_trips).items():
            g.set(n, cls=cls)

    metrics.watchdog_trips_total.add_collect(_trips)

    def _failovers(g):
        for client, n in dict(tracker.failover_dispatches).items():
            g.set(n, client=client)

    metrics.failover_dispatches_total.add_collect(_failovers)
    metrics.probe_total.add_collect(
        lambda g: [
            g.set(n, outcome=o) for o, n in tracker.probes.items()
        ]
    )

    def _faults(g):
        for kind, n in dict(tracker.faults).items():
            g.set(n, kind=kind)

    metrics.faults_total.add_collect(_faults)
    metrics.quarantines_total.add_collect(
        lambda g: g.set(tracker.quarantines)
    )
    metrics.reinstatements_total.add_collect(
        lambda g: g.set(tracker.reinstatements)
    )

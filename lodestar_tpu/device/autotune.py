"""Self-tuning device configuration: telemetry -> knobs, closed loop.

Every lever on the 10x path has been a MANUAL knob an operator must
set per host: the vpu/mxu limb backend (`ops/limbs.set_backend`), the
device-ingest gate (`bls/kernels.set_ingest_min_bucket`), the bucket
ladder's top rung (`bls/kernels.set_ladder_top`), and the rolling
bucket's latency budget (`TpuBlsVerifier.set_latency_budget_ms`). The
device telemetry layer (metrics/device.py, PR 10) can already SEE a
mistuned node — a stage departing its COVERAGE.md budget share, a
retrace storm, a warmup that never finishes — but nothing acted on
it. This module is the actuator:

  * `DeviceAutotuner` — at node start (after `jaxcache.enable()`, so
    repeat starts load compiled probes from the persistent cache and
    the whole tune is near-free), micro-benchmark a candidate grid
    {limb backend} x {ingest gate} x {ladder top} x {latency budget}
    using the real `bls/kernels.py` pipelines on synthetic sets,
    select a config (`select_config` — a pure function, unit-tested
    with stubbed measurements), apply it LIVE through the real
    setters, export `lodestar_autotune_*` gauges + a config-info
    series, and record a JSON artifact with the provenance stamp.
  * `DriftMonitor` — a background task that diffs the per-stage
    device/dispatch histograms into windowed stage shares, compares
    them against the COVERAGE.md "Device stage budget" table, and
    when a stage departs its share beyond a threshold for N
    consecutive windows schedules a BOUNDED re-tune — never mid-wave
    (gated through the verifier's `can_accept_work` / `is_quiescent`
    quiescence), never more often than the cooldown, never more than
    `max_retunes` times.

Grounding: the pipelined stage-scheduling of the BLS12-381 pairing
crypto-processor (PAPERS.md, arXiv 2201.07496) fixes a per-stage
budget at design time; a reconfigurable host must instead re-derive
it per deployment, which is exactly what the startup tune does. The
load model the grid is sized for is the committee-based consensus
signature stream of arXiv 2302.00418 (trickle aggregates + bulk
waves — the gate and ladder-top knobs trade between the two).

Measurement honesty: the probe pipeline is the real staged device
program (`run_verify_batch_async` -> prepare/miller/product/final);
ingest-stage probes would be multi-minute XLA compiles per bucket on
CPU, so off-TPU the tuner probes a small ladder rung and extrapolates
the gate/top/budget knobs through an explicit cost model
(`est_bucket_seconds`) whose assumptions are recorded per knob in the
decision's `rationale`. On a TPU host with budget, the probe runs at
real ladder rungs (batch-flat device cost makes the model exact
there). The decision artifact says which happened (`source`:
"measured" when every grid backend was probed, "partial" when the
budget cut the sweep short, "replay" for `--autotune-from`).

Cost of the tune itself, measured: the persistent cache removes the
XLA COMPILE share of the probe (where a tunneled TPU pays minutes —
final-exp alone compiled 357 s on the chip — repeat starts really
are near-free). What no cache can remove is jaxpr TRACING of the
interval-machinery-heavy stages, which dominates on CPU: a cold
probe on this 1-core container ran ~100 s (~39 s compile, the rest
trace), and a warm one ~99 s. So `--autotune startup` costs a TPU
node seconds after its first boot, and a CPU node ~2 min every boot
— which is why the mode defaults to off and the probe runs at the
smallest ladder rung off-TPU. Each measurement records its
`warm_seconds` so the artifact shows this share.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from dataclasses import asdict, dataclass

# ---------------------------------------------------------------------------
# COVERAGE.md stage budget (the offline table's live counterpart)
# ---------------------------------------------------------------------------

# Per-stage device budget in ms for the 2048-set production bucket —
# COVERAGE.md "Device stage budget" (the post window/static-ladder
# column, measured round 5 by tools/profile_prefix.py on one v5e,
# re-cut for the FUSED dispatch default of ISSUE 16: the eight
# per-stage rows collapse into the three fused programs — prepare =
# g2_sqrt + g2_subgroup + sswu_iso + cofactor + prepare_batch,
# pairing = miller + product, final unchanged; named sub-scopes
# keep the finer attribution inside the profiler). The drift monitor
# compares each stage's SHARE of windowed device time against these
# shares: absolute times shift with host and backend, but a stage
# whose share balloons past its budgeted fraction has regressed
# relative to its pipeline — the live analog of re-running the
# offline prefix budget. On hosts running the per-stage rollback
# composition the fused names accrue no time and the monitor sees
# no signal — it never false-fires there.
STAGE_BUDGET_MS = {
    "prepare": 288.0,  # 98.7 + 24.6 + 87.0 + 54.2 + 23.5
    "pairing": 78.4,  # 49.4 + 29.0
    "final": 16.2,
}


def budget_shares() -> dict[str, float]:
    """Each stage's budgeted fraction of total device time."""
    total = sum(STAGE_BUDGET_MS.values())
    return {s: ms / total for s, ms in STAGE_BUDGET_MS.items()}


# ---------------------------------------------------------------------------
# Candidate grid
# ---------------------------------------------------------------------------

DEFAULT_GRID = {
    "backend": ("vpu", "mxu"),
    "gate": (128, 256, 512),
    "top": (1024, 2048),
    "budget_ms": (25, 50, 100),
    "msm_window": (8, 12, 16),
    "pipeline_depth": (1, 2, 4),
}

# bulk (block-import / sync) buckets must clear well inside a slot;
# beyond this the top rung steps down (the measured v5e 2048 bucket
# runs 0.383 s — comfortably inside)
TOP_BUCKET_DEADLINE_S = 1.0


def parse_grid(spec: str | None) -> dict:
    """Parse an `--autotune-grid` spec into a grid dict.

    Format: semicolon-separated axes, comma-separated values:
      "backend=vpu;gate=128,256;top=2048;budget=50"
    Unnamed axes keep their DEFAULT_GRID values; unknown axes or
    values raise (a typo'd grid silently tuning the wrong space is
    worse than failing startup)."""
    grid = {k: tuple(v) for k, v in DEFAULT_GRID.items()}
    if not spec:
        return grid
    alias = {
        "budget": "budget_ms",
        "latency": "budget_ms",
        "window": "msm_window",
        "depth": "pipeline_depth",
    }
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        key, _, vals = part.partition("=")
        key = alias.get(key.strip(), key.strip())
        if key not in grid:
            raise ValueError(
                f"unknown autotune grid axis {key!r}; want "
                f"{sorted(grid)} (aliases: budget, latency)"
            )
        items = [v.strip() for v in vals.split(",") if v.strip()]
        if not items:
            raise ValueError(f"empty autotune grid axis {key!r}")
        if key == "backend":
            from ..ops import limbs

            for v in items:
                if v not in limbs.LIMB_BACKENDS:
                    raise ValueError(
                        f"unknown limb backend {v!r} in autotune grid"
                    )
            grid[key] = tuple(items)
        else:
            grid[key] = tuple(int(v) for v in items)
    _validate_grid_values(grid)
    return grid


def _validate_grid_values(grid: dict) -> None:
    """Reject knob values the setters would refuse — NOW, not after
    the probe budget is spent (an invalid `--autotune-grid top` that
    only explodes in apply_config aborts node startup minutes in)."""
    from ..bls import kernels

    for g in grid["gate"]:
        if g not in kernels._MID_RUNGS:
            raise ValueError(
                f"autotune grid gate {g} is not a ladder rung "
                f"{kernels._MID_RUNGS}"
            )
    for t in grid["top"]:
        if t < kernels._MID_RUNGS[-1]:
            raise ValueError(
                f"autotune grid top {t} below the largest mid rung "
                f"{kernels._MID_RUNGS[-1]}"
            )
    for b in grid["budget_ms"]:
        if b <= 0:
            raise ValueError(
                f"autotune grid latency budget {b} must be positive"
            )
    from ..ops import msm as _msm

    for w in grid["msm_window"]:
        if w not in _msm.SUPPORTED_WINDOWS:
            raise ValueError(
                f"autotune grid msm_window {w} not in "
                f"{_msm.SUPPORTED_WINDOWS}"
            )
    for d in grid["pipeline_depth"]:
        if d < 1:
            raise ValueError(
                f"autotune grid pipeline_depth {d} must be >= 1 "
                "(1 = synchronous dispatch)"
            )


@dataclass(frozen=True)
class TunedConfig:
    """One point of the knob space — everything apply() touches.
    msm_window == 0 means "leave the live window alone" (the default
    keeps pre-MSM decision artifacts replayable); pipeline_depth == 0
    the same for the verifier's wave-overlap depth."""

    limb_backend: str
    ingest_min_bucket: int
    ladder_top: int
    latency_budget_ms: float
    msm_window: int = 0
    pipeline_depth: int = 0

    def to_dict(self) -> dict:
        return asdict(self)


def current_config(verifier=None) -> TunedConfig:
    """The LIVE knob values (the tune's fallback and `previous`)."""
    from ..bls import kernels
    from ..ops import limbs

    from ..ops import msm

    budget_ms = 50.0
    fn = getattr(verifier, "latency_budget_ms", None)
    if fn is not None:
        budget_ms = float(fn())
    depth = 0
    dfn = getattr(verifier, "pipeline_depth", None)
    if dfn is not None:
        depth = int(dfn())
    return TunedConfig(
        limb_backend=limbs.get_backend(),
        ingest_min_bucket=kernels.ingest_min_bucket(),
        ladder_top=kernels.ladder_top(),
        latency_budget_ms=budget_ms,
        msm_window=msm.msm_window(),
        pipeline_depth=depth,
    )


@dataclass
class Measurement:
    """One probed (backend, bucket) point of the grid."""

    backend: str
    bucket: int
    pipeline: str  # which entry point was probed
    seconds_per_dispatch: float
    sets_per_sec: float
    runs: int
    warm_seconds: float  # first call: compile or persistent-cache load

    def to_dict(self) -> dict:
        return asdict(self)


# ---------------------------------------------------------------------------
# Selection (pure — unit-testable without a device or a compile)
# ---------------------------------------------------------------------------


def est_bucket_seconds(
    dispatch_s: float, probe_bucket: int, bucket: int, platform: str
) -> float:
    """Cost model extrapolating a measured per-dispatch time to other
    bucket sizes. On TPU per-dispatch device cost is batch-flat to
    ~2048 (COVERAGE.md; padding is nearly free), so time(b) ~= the
    probe time. On CPU XLA one core executes every lane, so cost is
    linear in the batch. Scaling DOWN is flat everywhere (fixed
    dispatch overhead dominates small buckets)."""
    if bucket <= probe_bucket:
        return dispatch_s
    if platform == "tpu":
        return dispatch_s
    return dispatch_s * bucket / probe_bucket


def select_config(
    grid: dict,
    measurements: list[Measurement],
    host_prep_s_per_set: float,
    platform: str,
) -> tuple[TunedConfig, dict]:
    """Pick the winning knob values from probe measurements.

    backend  — argmax sets/s among probed backends.
    gate     — smallest grid rung where a device bucket beats host
               prep of the same sets (est time(g) <= host_per_set*g);
               if the device never wins, the LARGEST rung (keep
               traffic on the host path it is better at).
    top      — largest grid top whose estimated bucket time fits
               TOP_BUCKET_DEADLINE_S; else the smallest.
    budget   — smallest grid latency budget >= 2x the gate bucket's
               estimated time (a deadline flush should not fire while
               an equivalent dispatch is still in flight); else the
               largest.

    Returns (config, rationale) where rationale records per knob what
    drove the choice — the artifact must be auditable."""
    if not measurements:
        raise ValueError("select_config needs at least one measurement")
    by_backend: dict[str, Measurement] = {}
    for m in measurements:
        cur = by_backend.get(m.backend)
        if cur is None or m.sets_per_sec > cur.sets_per_sec:
            by_backend[m.backend] = m
    best = max(by_backend.values(), key=lambda m: m.sets_per_sec)
    rationale: dict = {
        "backend": {
            "chosen": best.backend,
            "sets_per_sec": {
                b: round(m.sets_per_sec, 2)
                for b, m in sorted(by_backend.items())
            },
            "probed": sorted(by_backend),
            "skipped": sorted(
                set(grid["backend"]) - set(by_backend)
            ),
        }
    }
    est = lambda b: est_bucket_seconds(
        best.seconds_per_dispatch, best.bucket, b, platform
    )
    gates = sorted(grid["gate"])
    gate = next(
        (g for g in gates if est(g) <= host_prep_s_per_set * g),
        gates[-1],
    )
    rationale["gate"] = {
        "chosen": gate,
        "host_prep_s_per_set": round(host_prep_s_per_set, 6),
        "est_bucket_seconds": {
            g: round(est(g), 6) for g in gates
        },
        "model": "crossover: device bucket vs host prep of g sets"
        + ("" if platform == "tpu" else " (CPU linear-cost model)"),
    }
    tops = sorted(grid["top"])
    top = next(
        (t for t in reversed(tops) if est(t) <= TOP_BUCKET_DEADLINE_S),
        tops[0],
    )
    rationale["top"] = {
        "chosen": top,
        "deadline_s": TOP_BUCKET_DEADLINE_S,
        "est_bucket_seconds": {t: round(est(t), 6) for t in tops},
    }
    budgets = sorted(grid["budget_ms"])
    need_ms = 2.0 * est(gate) * 1000.0
    budget = next((b for b in budgets if b >= need_ms), budgets[-1])
    rationale["budget_ms"] = {
        "chosen": budget,
        "needed_ms": round(need_ms, 3),
        "model": "2x estimated gate-bucket dispatch time",
    }
    msm_window, msm_rationale = select_msm_window(
        grid.get("msm_window", DEFAULT_GRID["msm_window"]), platform
    )
    rationale["msm_window"] = msm_rationale
    depth, depth_rationale = select_pipeline_depth(
        grid.get("pipeline_depth", DEFAULT_GRID["pipeline_depth"]),
        platform,
    )
    rationale["pipeline_depth"] = depth_rationale
    cfg = TunedConfig(
        limb_backend=best.backend,
        ingest_min_bucket=gate,
        ladder_top=top,
        latency_budget_ms=float(budget),
        msm_window=msm_window,
        pipeline_depth=depth,
    )
    return cfg, rationale


def select_pipeline_depth(
    candidates, platform: str
) -> tuple[int, dict]:
    """Pick the verifier wave-overlap depth (bls/verifier.py).

    TPU: host prep (pubkey packing, limb conversion) and device
    execution run on different hardware, so any depth >= 2 hides the
    prep behind the in-flight wave; deeper queues only add latency
    and buffer footprint, so take the SMALLEST candidate >= 2.
    CPU emulation: the single core both preps and executes, there is
    no overlap to win — depth > 1 just reorders work and widens the
    flush window, so take the minimum candidate."""
    cands = sorted(set(int(d) for d in candidates))
    if platform == "tpu":
        chosen = next((d for d in cands if d >= 2), cands[-1])
        model = (
            "smallest depth >= 2: one prefetched wave hides host "
            "prep; deeper queues only add latency"
        )
    else:
        chosen = cands[0]
        model = (
            "min depth: one core preps AND executes, overlap "
            "hides nothing"
        )
    return chosen, {
        "chosen": chosen,
        "candidates": cands,
        "model": model,
    }


def select_msm_window(
    candidates, platform: str, rung: int | None = None
) -> tuple[int, dict]:
    """Pick the Pippenger window for the KZG MSM workload (ops/msm.py)
    from an explicit cost model of the device program at the dominant
    rung (the blob-width Lagrange lincomb).

    TPU: per-step device cost is batch-flat (COVERAGE.md), so the
    objective is SEQUENTIAL DEPTH — scatter steps (rung/PAR) + bucket
    reduction (2^(w-1)) + window combination (~255 doubles + nwin
    adds); small windows win until the bucket scan is negligible.
    CPU XLA: one core executes every lane, so the objective is TOTAL
    point adds — rung*nwin (scatter) + 2^w*nwin (reduction); the
    optimum sits near w = log2(rung). Both models and every
    candidate's estimate land in the rationale."""
    from ..ops import msm as _msm

    rung = rung or _msm.MSM_RUNGS[-1]
    cands = sorted(set(int(w) for w in candidates))

    def seq_steps(w):
        nwin = _msm.num_windows(w)
        return rung // _msm.PAR + (1 << (w - 1)) + 255 + nwin

    def total_adds(w):
        nwin = _msm.num_windows(w)
        return rung * nwin + (1 << w) * nwin

    model = seq_steps if platform == "tpu" else total_adds
    chosen = min(cands, key=model)
    return chosen, {
        "chosen": chosen,
        "rung": rung,
        "model": (
            "sequential device steps (batch-flat per-step cost)"
            if platform == "tpu"
            else "total point adds (CPU linear per-lane cost)"
        ),
        "estimates": {w: model(w) for w in cands},
    }


# ---------------------------------------------------------------------------
# Applied-decision module state (provenance + bench replay)
# ---------------------------------------------------------------------------

_APPLIED: dict | None = None
_APPLY_LOCK = threading.Lock()


def applied_decision() -> dict | None:
    """The last decision applied in this process (None = knobs came
    from env/CLI, untouched by the tuner)."""
    return _APPLIED


def provenance_fields() -> dict:
    """Tuned-config fields for the bench provenance stamp
    (utils/provenance.py): every BENCH_*/MULTICHIP_* artifact must
    record what configuration produced it."""
    d = _APPLIED
    out: dict = {
        "autotune_mode": d.get("mode", "off") if d else "off",
        "autotune_source": d.get("source", "env") if d else "env",
    }
    if d:
        out["autotune_trigger"] = d.get("trigger")
    return out


def _record_applied(decision: dict) -> None:
    global _APPLIED
    with _APPLY_LOCK:
        _APPLIED = decision


def apply_config(config: TunedConfig, verifier=None) -> None:
    """Push a config through the REAL setters, re-warming exactly
    ONCE against the FINAL eligibility: both bucket knobs apply with
    their own rewarm kicks deferred (a kick between them would
    compile rungs of a half-applied config, possibly on the outgoing
    backend), then either the backend switch re-warms (its
    warm-registry invalidation kicks at the now-final gate/ladder)
    or, with no switch, one explicit kick covers whatever the knob
    changes left cold — e.g. a re-tuned ladder top that was never
    compiled, which a cold-fallback verifier would otherwise route
    host_cold forever."""
    from ..bls import kernels
    from ..ops import limbs
    from ..ops import msm as _msm

    switching = limbs.get_backend() != config.limb_backend
    kernels.set_ladder_top(config.ladder_top, rewarm=False)
    kernels.set_ingest_min_bucket(
        config.ingest_min_bucket, rewarm=False
    )
    if config.msm_window:
        # rewarm deferred like the bucket knobs: a kick here would
        # compile MSM programs against a limb backend the switch
        # below is about to clear-caches away
        _msm.set_msm_window(config.msm_window, rewarm=False)
    if switching:
        # the switch's registry invalidation re-kicks BOTH workloads'
        # warmups (BLS ingest + MSM rungs) at the final knob state
        limbs.set_backend(config.limb_backend)
    else:
        if kernels._WARMUP_STARTED:
            newly = tuple(
                b
                for b in kernels.default_warmup_sizes()
                if not kernels.ingest_is_warm(b)
            )
            if newly:
                kernels.warmup_ingest(newly)
        # cold MSM rungs (a re-tuned window) re-warm when the process
        # opted in; warm rungs make this a no-op
        _msm.rewarm_async()
    fn = getattr(verifier, "set_latency_budget_ms", None)
    if fn is not None:
        fn(config.latency_budget_ms)
    if config.pipeline_depth:
        # 0 = leave the live overlap depth alone (pre-pipeline
        # decision artifacts stay replayable)
        dfn = getattr(verifier, "set_pipeline_depth", None)
        if dfn is not None:
            dfn(config.pipeline_depth)


def load_decision(path: str) -> dict:
    """Read a recorded autotune decision artifact (AUTOTUNE*.json)."""
    with open(path) as f:
        d = json.load(f)
    if "config" not in d:
        raise ValueError(f"{path}: not an autotune decision artifact")
    return d


def apply_decision(
    decision: dict, verifier=None, source: str = "replay"
) -> TunedConfig:
    """Replay a recorded decision (bench.py / tools/bench_*
    --autotune-from): apply its config through the real setters and
    mark this process's provenance as a replay."""
    c = decision["config"]
    cfg = TunedConfig(
        limb_backend=c["limb_backend"],
        ingest_min_bucket=int(c["ingest_min_bucket"]),
        ladder_top=int(c["ladder_top"]),
        latency_budget_ms=float(c["latency_budget_ms"]),
        # pre-MSM artifacts carry no window; 0 leaves the live one
        msm_window=int(c.get("msm_window", 0)),
        pipeline_depth=int(c.get("pipeline_depth", 0)),
    )
    apply_config(cfg, verifier=verifier)
    _record_applied(
        {
            **{
                k: decision.get(k)
                for k in ("mode", "trigger")
                if k in decision
            },
            "source": source,
            "config": cfg.to_dict(),
        }
    )
    return cfg


# ---------------------------------------------------------------------------
# The tuner
# ---------------------------------------------------------------------------

DEFAULT_BUDGET_MS = 30_000.0
ARTIFACT_PATH = "AUTOTUNE.json"


class DeviceAutotuner:
    """Micro-benchmark the candidate grid and apply the winner.

    verifier: the live TpuBlsVerifier (None = tune kernel knobs only).
    budget_ms: wall-clock ceiling for one tune() — the FIRST backend
      is always measured (otherwise the tuner could never decide);
      later candidates are skipped when the remaining budget cannot
      cover a candidate the size of the last one (source: "partial").
    grid: parse_grid() output (None = DEFAULT_GRID).
    bench: injectable (backend, bucket) -> Measurement for tests —
      the offline unit suite stubs this so NO compile enters tier-1.
    probe_bucket: ladder rung the probes run at (None = auto: 4 off
      TPU where per-lane cost is linear and compiles are slow; the
      smallest grid gate on TPU where batch-flat cost makes bigger
      probes exact and the persistent cache makes them cheap).
    """

    def __init__(
        self,
        verifier=None,
        budget_ms: float = DEFAULT_BUDGET_MS,
        grid: dict | None = None,
        bench=None,
        probe_bucket: int | None = None,
        artifact_path: str | None = ARTIFACT_PATH,
        mode: str = "startup",
        clock=time.monotonic,
        logger=None,
        executor=None,
        health=None,
    ):
        self.verifier = verifier
        # device/health.py DeviceHealthTracker: while the device is
        # quarantined every tune is a no-op — the tuner must neither
        # probe a sick device nor mutate the knob config the host
        # failover path was frozen under
        self.health = health
        # node DeviceExecutor (device/executor.py): probes are
        # maintenance-class work — between candidates the tuner
        # yields the device to pending deadline traffic
        self.executor = executor
        self.budget_ms = float(budget_ms)
        self.grid = grid or parse_grid(None)
        self._bench = bench or self._measure_real
        self._probe_bucket = probe_bucket
        self.artifact_path = artifact_path
        self.mode = mode
        self._clock = clock
        if logger is None:
            from ..logger import get_logger

            logger = get_logger("autotune")
        self.log = logger
        self._lock = threading.Lock()
        self._probe_inputs_cache: dict[int, tuple] = {}
        # gauges (bind_autotune_collectors samples these at scrape)
        self.runs = 0
        self.drift_retunes = 0
        self.candidates_measured = 0
        self.last_duration_s = 0.0
        self.best_sets_per_sec = 0.0
        self.suspended_runs = 0
        self.last_decision: dict | None = None

    # -- probing --------------------------------------------------------

    def _platform(self) -> str:
        import jax

        return jax.default_backend()

    def probe_bucket(self) -> int:
        if self._probe_bucket is not None:
            return self._probe_bucket
        return (
            min(self.grid["gate"])
            if self._platform() == "tpu"
            else 4
        )

    def _probe_inputs(self, n: int):
        """n valid (pk, H, sig) device batches + rand bits + mask —
        the legacy-pipeline shape (host-hashed, like
        tools/bench_mesh_sweep.build_inputs). Cached per bucket: the
        fixture is backend-independent host data."""
        if n in self._probe_inputs_cache:
            return self._probe_inputs_cache[n]
        import jax.numpy as jnp

        from ..bls import kernels
        from ..crypto.bls import curve as oc
        from ..ops import curve as C

        hs = [oc.g2_mul(oc.G2_GEN, 7 + i) for i in range(n)]
        pks, sigs = [], []
        for i, h in enumerate(hs):
            sk = 100 + i
            pks.append(oc.g1_mul(oc.G1_GEN, sk))
            sigs.append(oc.g2_mul(h, sk))
        pk_dev = C.g1_batch_from_ints(pks)
        h_pt = C.g2_batch_from_ints(hs)
        sig_dev = C.g2_batch_from_ints(sigs)
        rand = [(0x9E37 + 2 * i) | 1 for i in range(n)]
        bits = C.scalars_to_bits(rand, kernels.RAND_BITS)
        mask = jnp.ones(n, bool)
        out = (pk_dev, (h_pt.x, h_pt.y), sig_dev, bits, mask)
        self._probe_inputs_cache[n] = out
        return out

    def _measure_real(self, backend: str, bucket: int) -> Measurement:
        """Probe the REAL staged pipeline (prepare/miller/product/
        final — the per-set device math the backend choice changes)
        at `bucket`, through the persistent compilation cache."""
        from ..bls import kernels
        from ..ops import limbs
        from ..utils import jaxcache

        jaxcache.enable()
        if limbs.get_backend() != backend:
            # transient probe switch: invalidate warm marks but do
            # NOT kick a background warmup for a candidate that may
            # lose — the compile storm would also run concurrently
            # with the timing loop and skew the measurement
            limbs.set_backend(backend, rewarm=False)
        inputs = self._probe_inputs(bucket)
        t0 = self._clock()
        ok = bool(kernels.run_verify_batch_async(*inputs))
        warm_s = self._clock() - t0
        if not ok:
            raise RuntimeError(
                f"autotune probe verify failed (backend={backend})"
            )
        times = []
        for _ in range(3):
            t0 = self._clock()
            bool(kernels.run_verify_batch_async(*inputs))
            times.append(self._clock() - t0)
        per_dispatch = min(times)
        return Measurement(
            backend=backend,
            bucket=bucket,
            pipeline="batch",
            seconds_per_dispatch=per_dispatch,
            sets_per_sec=bucket / per_dispatch if per_dispatch else 0.0,
            runs=len(times),
            warm_seconds=warm_s,
        )

    def _measure_host_prep(self) -> float:
        """Host-path per-set cost (decompression + hash-to-G2, the
        work a device-ingest bucket replaces) — the other arm of the
        gate crossover. Distinct messages/signatures defeat the lru
        caches so this measures cold cost, like live traffic."""
        from ..bls import api
        from ..crypto.bls.signature import sign

        k = 6
        fixtures = []
        for i in range(k):
            msg = bytes([0xA0 + i]) * 32
            fixtures.append((sign(211 + i, msg), msg))
        t0 = self._clock()
        for sig_bytes, msg in fixtures:
            api.decompress_signature(sig_bytes)
            api.message_to_g2(msg)
        return max(1e-9, (self._clock() - t0) / k)

    # -- the tune -------------------------------------------------------

    def _maintenance_checkpoint(self) -> None:
        """Between candidate probes, yield the device to pending
        deadline work through the executor's maintenance gate (no
        executor wired = no-op, the pre-executor behavior). A startup
        tune inside the drift monitor's drain window sees no pending
        deadline work by construction and does not stall."""
        ex = self.executor
        if ex is not None:
            try:
                ex.maintenance_checkpoint()
            except Exception:
                pass

    def tune(self, trigger: str = "startup") -> dict:
        """Measure, select, APPLY, export, record. Returns the
        decision dict (also written to `artifact_path`)."""
        with self._lock:
            return self._tune_locked(trigger)

    def _backend_candidates(
        self, prev: TunedConfig, platform: str
    ) -> tuple[list[str], dict[str, str]]:
        """The backends worth probing on this platform. Off-TPU the
        int8 'mxu' decomposition is KNOWN slower — strictly more MACs
        with no matrix unit to pay for them (COVERAGE.md limb-backend
        study) — and its probe costs a multi-minute cache-clearing
        recompile, so policy excludes it rather than measuring the
        foregone conclusion. An operator who pins the grid to mxu
        alone gets it probed anyway (explicit wins over policy)."""
        backends = list(self.grid["backend"])
        policy: dict[str, str] = {}
        if platform != "tpu" and len(backends) > 1:
            for b in list(backends):
                if b == "mxu":
                    backends.remove(b)
                    policy[b] = (
                        f"no matrix unit on {platform!r}: int8 "
                        "decomposition is strictly more MACs "
                        "(COVERAGE.md limb-backend study)"
                    )
        # probe the live backend first: its traces may already be warm
        backends.sort(key=lambda b: b != prev.limb_backend)
        return backends, policy

    def _tune_locked(self, trigger: str) -> dict:
        if (
            self.health is not None
            and not self.health.device_allowed()
        ):
            # frozen-config invariant: a quarantined device gets no
            # probes and the live config stays exactly as it was at
            # quarantine time (scenario fabric asserts this)
            self.suspended_runs += 1
            decision = {
                "source": "suspended",
                "trigger": trigger,
                "reason": "device quarantined",
                "state": self.health.state.value,
                # the frozen live config — collectors index ["config"]
                "config": current_config(self.verifier).to_dict(),
            }
            self.last_decision = decision
            self.log.warn(
                "autotune suspended: device quarantined",
                {"trigger": trigger},
            )
            return decision
        t_start = self._clock()
        prev = current_config(self.verifier)
        platform = self._platform()
        probe = self.probe_bucket()
        spent_ms = lambda: (self._clock() - t_start) * 1000.0
        host_prep = self._measure_host_prep()
        measurements: list[Measurement] = []
        backends, policy_skipped = self._backend_candidates(
            prev, platform
        )
        last_cost_ms = 0.0
        for b in backends:
            # a candidate on another backend pays a cache-clearing
            # recompile of every probe trace — estimate it an order
            # above the last (warm-ish) candidate so the budget check
            # errs toward skipping rather than blowing the ceiling
            est_ms = last_cost_ms * (
                10.0 if b != prev.limb_backend else 1.0
            )
            if measurements and (spent_ms() + est_ms > self.budget_ms):
                self.log.warn(
                    "autotune budget exhausted; skipping backend",
                    {"backend": b, "spent_ms": round(spent_ms(), 1)},
                )
                continue
            self._maintenance_checkpoint()
            t_c = self._clock()
            try:
                m = self._bench(b, probe)
            except Exception as e:
                self.log.warn(
                    "autotune probe failed; backend skipped",
                    {"backend": b, "err": repr(e)},
                )
                continue
            last_cost_ms = (self._clock() - t_c) * 1000.0
            measurements.append(m)
            self.candidates_measured += 1
        if measurements:
            config, rationale = select_config(
                self.grid, measurements, host_prep, platform
            )
            # "measured" is judged against the backends worth probing
            # on this platform; policy exclusions are recorded, not
            # counted as a budget shortfall
            source = (
                "measured"
                if {m.backend for m in measurements} >= set(backends)
                else "partial"
            )
            if policy_skipped:
                rationale["backend"]["policy_skipped"] = policy_skipped
            self.best_sets_per_sec = max(
                m.sets_per_sec for m in measurements
            )
        else:
            # nothing measured inside the budget: keep the live knobs
            config, rationale = prev, {
                "fallback": "no candidate fit the budget"
            }
            source = "default"
        # apply_config re-warms once at the final eligibility — that
        # also repairs whatever the probes' rewarm-suppressed backend
        # switches left invalidated
        apply_config(config, verifier=self.verifier)
        self.runs += 1
        if trigger.startswith("drift"):
            self.drift_retunes += 1
        self.last_duration_s = (self._clock() - t_start)
        decision = {
            "mode": self.mode,
            "trigger": trigger,
            "source": source,
            "platform": platform,
            "probe_bucket": probe,
            "config": config.to_dict(),
            "previous": prev.to_dict(),
            "host_prep_seconds_per_set": round(host_prep, 6),
            "measurements": [m.to_dict() for m in measurements],
            "rationale": rationale,
            "budget_ms": self.budget_ms,
            "spent_ms": round(spent_ms(), 1),
            "grid": {k: list(v) for k, v in self.grid.items()},
        }
        _record_applied(decision)
        self.last_decision = decision
        self._write_artifact(decision)
        self.log.info(
            "autotune applied",
            {
                "trigger": trigger,
                "source": source,
                "backend": config.limb_backend,
                "gate": config.ingest_min_bucket,
                "top": config.ladder_top,
                "latency_budget_ms": config.latency_budget_ms,
                "spent_ms": decision["spent_ms"],
            },
        )
        return decision

    def _write_artifact(self, decision: dict) -> None:
        if not self.artifact_path:
            return
        try:
            from ..utils.provenance import provenance

            payload = dict(decision, provenance=provenance())
            with open(self.artifact_path, "w") as f:
                json.dump(payload, f, indent=2)
                f.write("\n")
        except Exception as e:
            # the artifact is a record, not a dependency — a read-only
            # filesystem must not fail the tune that already applied
            self.log.warn(
                "autotune artifact write failed",
                {"path": self.artifact_path, "err": repr(e)},
            )


# ---------------------------------------------------------------------------
# Drift monitor
# ---------------------------------------------------------------------------


class DriftMonitor:
    """Watch the live per-stage times against the COVERAGE.md budget
    shares; schedule a bounded re-tune when a stage drifts.

    Sampling: each window diffs the telemetry's cumulative per-stage
    seconds (`snapshot_stage_seconds`) — device (block_until_ready)
    seconds when `--device-timing sync` populates them, else dispatch
    wall seconds. Windows with less than `min_window_s` of total
    budgeted-stage time carry no signal and are skipped (an idle node
    must not retune itself off noise).

    Trigger: a stage whose share deviates from its budget share by
    more than `threshold` (absolute) for `windows` CONSECUTIVE
    windows. Bounded: at most `max_retunes` drift re-tunes, at least
    `cooldown_s` apart, and NEVER mid-wave — the re-tune only fires
    when the verifier is quiescent (`can_accept_work` and
    `is_quiescent`); while it is not, the trigger stays pending and
    `retunes_blocked` counts the deferrals."""

    def __init__(
        self,
        tuner: DeviceAutotuner,
        telemetry,
        verifier=None,
        shares: dict[str, float] | None = None,
        threshold: float = 0.15,
        windows: int = 3,
        interval_s: float = 30.0,
        cooldown_s: float = 600.0,
        max_retunes: int = 8,
        min_window_s: float = 0.05,
        clock=time.monotonic,
        executor=None,
        health=None,
    ):
        self.tuner = tuner
        # device/health.py: a pending re-tune DEFERS while the device
        # is quarantined (pending_stage is kept, so the re-tune lands
        # after reinstatement instead of being lost)
        self.health = (
            health
            if health is not None
            else getattr(tuner, "health", None)
        )
        self.telemetry = telemetry
        self.verifier = (
            verifier if verifier is not None else tuner.verifier
        )
        # node DeviceExecutor (device/executor.py): when wired, a
        # re-tune runs inside executor.drained() — intake closes for
        # EVERY device client and quiescence is awaited centrally,
        # with zero calls to the verifier's hold_intake. Without one
        # the legacy hold_intake/is_quiescent dance below still works
        # (standalone verifiers, tests).
        self.executor = (
            executor
            if executor is not None
            else getattr(tuner, "executor", None)
        )
        self.shares = shares or budget_shares()
        self.threshold = threshold
        self.windows = windows
        self.interval_s = interval_s
        self.cooldown_s = cooldown_s
        self.max_retunes = max_retunes
        self.min_window_s = min_window_s
        self._clock = clock
        self._last_cum: dict[str, float] = {}
        self._last_retune_t: float | None = None
        self._task = None
        # gauges (bind_autotune_collectors)
        self.last_shares: dict[str, float] = {}
        self.streaks: dict[str, int] = {s: 0 for s in self.shares}
        self.windows_sampled = 0
        self.retunes = 0
        self.retunes_blocked = 0
        self.pending_stage: str | None = None

    def _cumulative(self) -> dict[str, float]:
        disp, dev = self.telemetry.snapshot_stage_seconds()
        picked = dev if any(s in dev for s in self.shares) else disp
        return {s: picked.get(s, 0.0) for s in self.shares}

    def sample(self) -> dict[str, float]:
        """One drift window. Returns the observed shares ({} = no
        signal this window)."""
        cum = self._cumulative()
        if not self._last_cum:
            self._last_cum = cum
            return {}
        delta = {
            s: max(0.0, cum[s] - self._last_cum.get(s, 0.0))
            for s in self.shares
        }
        self._last_cum = cum
        total = sum(delta.values())
        if total < self.min_window_s:
            return {}
        shares = {s: d / total for s, d in delta.items()}
        self.last_shares = shares
        self.windows_sampled += 1
        for s, share in shares.items():
            if abs(share - self.shares[s]) > self.threshold:
                self.streaks[s] += 1
            else:
                self.streaks[s] = 0
        for s, n in self.streaks.items():
            if n >= self.windows and self.pending_stage is None:
                if self.retunes >= self.max_retunes:
                    continue
                now = self._clock()
                if (
                    self._last_retune_t is not None
                    and now - self._last_retune_t < self.cooldown_s
                ):
                    continue
                self.pending_stage = s
        return shares

    def _verifier_quiet(self) -> bool:
        """No in-flight/queued verifier work. Prefers is_quiescent
        (valid inside the intake hold); can_accept_work is only the
        fallback for verifiers without it — it must not be consulted
        under hold_intake, which forces it False by design."""
        v = self.verifier
        if v is None:
            return True
        quiet = getattr(v, "is_quiescent", None)
        if quiet is not None:
            return bool(quiet())
        accept = getattr(v, "can_accept_work", None)
        return accept is None or bool(accept())

    def maybe_retune(self) -> bool:
        """Fire the pending re-tune if the device is quiescent.
        Returns True when a re-tune ran. BLOCKING (the tune probes
        the device) — the async loop runs it in an executor thread.
        With a node DeviceExecutor wired the whole window is one
        `executor.drained()`: intake closes for every device client,
        quiescence (including the verifier's probe) is awaited
        centrally, and `hold_intake` is never called. Without one,
        the quiescence checked here is HELD for the tune's duration
        via the verifier's intake hold (can_accept_work -> False), so
        the processor-fed gossip path cannot start waves under the
        knob switches; direct callers (block import) can still land a
        wave mid-tune, which costs recompile latency, not
        correctness."""
        stage = self.pending_stage
        if stage is None:
            return False
        if (
            self.health is not None
            and not self.health.device_allowed()
        ):
            # defer, don't drop: pending_stage survives quarantine so
            # the re-tune fires once the device is reinstated
            self.retunes_blocked += 1
            return False
        if self.executor is not None:
            # executor path: one drain closes intake for EVERY device
            # client (verifier, kzg bulk, warmup) and awaits their
            # quiescence probes — the hold_intake/is_quiescent dance
            # is the executor's job now
            with self.executor.drained() as quiet:
                if not quiet:
                    self.retunes_blocked += 1
                    return False
                self.pending_stage = None
                self.tuner.tune(trigger=f"drift:{stage}")
        else:
            hold = getattr(self.verifier, "hold_intake", None)
            ctx = (
                hold() if hold is not None else contextlib.nullcontext()
            )
            with ctx:
                # quiescence is checked INSIDE the hold: a wave
                # admitted between an outside check and the hold
                # engaging would run under the tune's knob switches
                if not self._verifier_quiet():
                    self.retunes_blocked += 1
                    return False
                self.pending_stage = None
                self.tuner.tune(trigger=f"drift:{stage}")
        self.retunes += 1
        self._last_retune_t = self._clock()
        self.streaks = {s: 0 for s in self.shares}
        # the tune's own probe dispatches went through the
        # instrumented stage entry points — drop the accumulated
        # baseline so the next window diffs from POST-tune state
        # instead of reading the probe's bucket-4 profile as drift
        self._last_cum = {}
        return True

    async def run(self):
        """Background loop (node.py spawns this as a task in adaptive
        mode; cancel to stop)."""
        import asyncio

        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                self.sample()
                if self.pending_stage is not None:
                    await loop.run_in_executor(None, self.maybe_retune)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.tuner.log.warn(
                    "drift monitor window failed", {"err": repr(e)}
                )


# ---------------------------------------------------------------------------
# /metrics bridging (the addCollect pattern every service uses)
# ---------------------------------------------------------------------------


def bind_autotune_collectors(
    metrics, tuner: DeviceAutotuner, monitor: DriftMonitor | None = None
) -> None:
    """Wire the m.autotune registry namespace (metrics/beacon.py) to
    sample the tuner/monitor at scrape time."""
    metrics.runs_total.add_collect(lambda g: g.set(tuner.runs))
    metrics.retunes_total.add_collect(
        lambda g: g.set(tuner.drift_retunes)
    )
    metrics.candidates_measured_total.add_collect(
        lambda g: g.set(tuner.candidates_measured)
    )
    metrics.last_duration_seconds.add_collect(
        lambda g: g.set(tuner.last_duration_s)
    )
    metrics.best_sets_per_sec.add_collect(
        lambda g: g.set(tuner.best_sets_per_sec)
    )

    def _selected(g):
        d = tuner.last_decision or applied_decision()
        cfg = (
            d["config"]
            if d is not None
            else current_config(tuner.verifier).to_dict()
        )
        g.set(cfg["ingest_min_bucket"], knob="ingest_min_bucket")
        g.set(cfg["ladder_top"], knob="ladder_top")
        g.set(cfg["latency_budget_ms"], knob="latency_budget_ms")
        # 0 = decision predates the knob / left the live window alone
        g.set(cfg.get("msm_window") or 0, knob="msm_window")
        g.set(cfg.get("pipeline_depth") or 0, knob="pipeline_depth")

    metrics.selected.add_collect(_selected)

    info_seen: set[tuple] = set()

    def _info(g):
        d = tuner.last_decision or applied_decision()
        cfg = (
            d["config"]
            if d is not None
            else current_config(tuner.verifier).to_dict()
        )
        key = (
            cfg["limb_backend"],
            tuner.mode,
            (d or {}).get("source", "env"),
        )
        # a re-tune that changes backend/source must retire the old
        # info series (the registry keeps every label tuple ever set
        # — two series at 1 would make the live config ambiguous)
        for old in info_seen - {key}:
            g.set(0, backend=old[0], mode=old[1], source=old[2])
        info_seen.add(key)
        g.set(1, backend=key[0], mode=key[1], source=key[2])

    metrics.config_info.add_collect(_info)

    def _shares(g):
        if monitor is None:
            return
        for s, share in monitor.last_shares.items():
            g.set(share, stage=s)

    def _budget_shares(g):
        if monitor is None:
            return
        for s, share in monitor.shares.items():
            g.set(share, stage=s)

    def _streaks(g):
        if monitor is None:
            return
        for s, n in monitor.streaks.items():
            g.set(n, stage=s)

    metrics.stage_share.add_collect(_shares)
    metrics.stage_budget_share.add_collect(_budget_shares)
    metrics.drift_windows.add_collect(_streaks)
    metrics.retunes_blocked_total.add_collect(
        lambda g: g.set(monitor.retunes_blocked if monitor else 0)
    )

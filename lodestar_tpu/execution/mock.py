"""Mock execution engine for tests and dev chains.

Reference analog: ExecutionEngineMockBackend (execution/engine/mock.ts)
— keeps a hash-chained payload tree, answers newPayload/fcU/getPayload
with configurable verdicts, builds payloads for requested attributes.
"""

from __future__ import annotations

from hashlib import sha256

from ..params import ForkSeq
from .engine import (
    ExecutionPayloadStatus,
    ForkchoiceResponse,
    ForkchoiceState,
    GetPayloadResponse,
    PayloadAttributes,
    PayloadStatus,
)


class MockExecutionEngine:
    """In-process IExecutionEngine with scriptable verdicts."""

    def __init__(self, types, genesis_block_hash: bytes = b"\x00" * 32):
        self.types = types
        self.known_blocks: dict[bytes, dict] = {genesis_block_hash: {}}
        self.head: bytes = genesis_block_hash
        self.finalized: bytes = genesis_block_hash
        # scripting hooks
        self.payload_verdict = ExecutionPayloadStatus.VALID
        self.fcu_verdict = ExecutionPayloadStatus.VALID
        self._payloads: dict[bytes, tuple[str, PayloadAttributes, bytes]] = {}
        self._payload_seq = 0
        self.calls: list[tuple] = []

    # -- IExecutionEngine --------------------------------------------------

    async def notify_new_payload(
        self,
        fork: str,
        payload,
        versioned_hashes=None,
        parent_root=None,
        execution_requests=None,
    ) -> PayloadStatus:
        self.calls.append(("newPayload", bytes(payload.block_hash)))
        if self.payload_verdict is not ExecutionPayloadStatus.VALID:
            return PayloadStatus(self.payload_verdict)
        parent = bytes(payload.parent_hash)
        if parent not in self.known_blocks:
            return PayloadStatus(ExecutionPayloadStatus.SYNCING)
        bh = bytes(payload.block_hash)
        self.known_blocks[bh] = {"parent": parent}
        return PayloadStatus(
            ExecutionPayloadStatus.VALID, latest_valid_hash=bh
        )

    async def notify_forkchoice_update(
        self,
        fork: str,
        state: ForkchoiceState,
        attributes: PayloadAttributes | None = None,
    ) -> ForkchoiceResponse:
        self.calls.append(("fcU", bytes(state.head_block_hash)))
        if self.fcu_verdict is not ExecutionPayloadStatus.VALID:
            return ForkchoiceResponse(PayloadStatus(self.fcu_verdict))
        self.head = bytes(state.head_block_hash)
        self.finalized = bytes(state.finalized_block_hash)
        payload_id = None
        if attributes is not None:
            self._payload_seq += 1
            payload_id = self._payload_seq.to_bytes(8, "big")
            self._payloads[payload_id] = (fork, attributes, self.head)
        return ForkchoiceResponse(
            PayloadStatus(ExecutionPayloadStatus.VALID), payload_id
        )

    async def get_payload(
        self, fork: str, payload_id: bytes
    ) -> GetPayloadResponse:
        self.calls.append(("getPayload", payload_id))
        fork_at_req, attrs, parent_hash = self._payloads[payload_id]
        payload = self._build(fork, attrs, parent_hash)
        self.known_blocks[bytes(payload.block_hash)] = {
            "parent": parent_hash
        }
        return GetPayloadResponse(payload, block_value=10**9)

    async def get_payload_bodies_by_hash(self, fork: str, hashes):
        return [None for _ in hashes]

    # -- internals ---------------------------------------------------------

    def _build(self, fork: str, attrs: PayloadAttributes, parent: bytes):
        payload = self.types.by_fork[fork].ExecutionPayload.default()
        payload.parent_hash = parent
        payload.fee_recipient = bytes(attrs.suggested_fee_recipient)
        payload.prev_randao = bytes(attrs.prev_randao)
        payload.timestamp = int(attrs.timestamp)
        payload.block_number = len(self.known_blocks)
        payload.gas_limit = 30_000_000
        if (
            int(ForkSeq[fork]) >= ForkSeq.capella
            and attrs.withdrawals is not None
        ):
            payload.withdrawals = list(attrs.withdrawals)
        payload.block_hash = sha256(
            b"mock-exec"
            + parent
            + int(attrs.timestamp).to_bytes(8, "little")
            + bytes(attrs.prev_randao)
        ).digest()
        return payload

"""Execution-layer integration: engine API + builder API.

Reference analog: beacon-node/src/execution/ — `IExecutionEngine`
(engine/interface.ts:133-181), `ExecutionEngineHttp` (engine/http.ts:115),
`ExecutionEngineMockBackend` (engine/mock.ts), and the MEV-boost
builder client (builder/http.ts:60).
"""

from .engine import (
    EngineOfflineError,
    ExecutionEngineError,
    ExecutionPayloadStatus,
    ForkchoiceState,
    PayloadAttributes,
    PayloadStatus,
    ResilientEngine,
)
from .mock import MockExecutionEngine

__all__ = [
    "EngineOfflineError",
    "ExecutionEngineError",
    "ExecutionPayloadStatus",
    "ForkchoiceState",
    "PayloadAttributes",
    "PayloadStatus",
    "MockExecutionEngine",
    "ResilientEngine",
]

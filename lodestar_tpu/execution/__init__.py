"""Execution-layer integration: engine API + builder API.

Reference analog: beacon-node/src/execution/ — `IExecutionEngine`
(engine/interface.ts:133-181), `ExecutionEngineHttp` (engine/http.ts:115),
`ExecutionEngineMockBackend` (engine/mock.ts), and the MEV-boost
builder client (builder/http.ts:60).
"""

from .engine import (
    ExecutionPayloadStatus,
    ForkchoiceState,
    PayloadAttributes,
    PayloadStatus,
)
from .mock import MockExecutionEngine

__all__ = [
    "ExecutionPayloadStatus",
    "ForkchoiceState",
    "PayloadAttributes",
    "PayloadStatus",
    "MockExecutionEngine",
]

"""External block builder (MEV-boost relay) client.

Reference analog: ExecutionBuilderHttp (execution/builder/http.ts:60)
over the builder-specs REST API: registerValidator, getHeader (bid for
a blinded block), submitBlindedBlock (reveal). `MockRelay` is the test
double (reference uses mocked relays in unit tests).
"""

from __future__ import annotations

import asyncio
import json
import urllib.error
import urllib.request
from dataclasses import dataclass


class BuilderError(Exception):
    pass


@dataclass
class BuilderBid:
    header: object  # ExecutionPayloadHeader value
    value: int
    pubkey: bytes
    # deneb+: the bid commits to its blob set (builder-specs
    # BuilderBid.blob_kzg_commitments); None = pre-deneb / not provided
    blob_kzg_commitments: list | None = None


class ExecutionBuilderHttp:
    """builder-specs REST client (http.ts:60). Faulty relays are
    circuit-broken like the reference: after `max_faults` consecutive
    errors the builder is disabled until re-enabled."""

    def __init__(self, base_url: str, types, timeout: float = 5.0,
                 max_faults: int = 3):
        self.base_url = base_url.rstrip("/")
        self.types = types
        self.timeout = timeout
        self.enabled = True
        self.faults = 0
        self.max_faults = max_faults

    async def _call(self, method: str, path: str, body=None):
        if not self.enabled:
            raise BuilderError(
                "builder circuit-broken after repeated faults"
            )

        def _do():
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                self.base_url + path,
                data=data,
                method=method,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else None

        try:
            out = await asyncio.get_event_loop().run_in_executor(None, _do)
            self.faults = 0
            return out
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            self.faults += 1
            if self.faults >= self.max_faults:
                self.enabled = False
            raise BuilderError(str(e)) from e

    async def register_validators(self, registrations: list[dict]) -> None:
        await self._call(
            "POST", "/eth/v1/builder/validators", registrations
        )

    async def get_header(
        self, slot: int, parent_hash: bytes, pubkey: bytes
    ) -> BuilderBid | None:
        out = await self._call(
            "GET",
            f"/eth/v1/builder/header/{slot}/0x{parent_hash.hex()}"
            f"/0x{pubkey.hex()}",
        )
        if out is None:
            return None
        msg = out["data"]["message"]
        hdr = msg["header"]
        fork = out.get("version", "bellatrix")
        header = self._header_from_json(fork, hdr)
        comms = msg.get("blob_kzg_commitments")
        return BuilderBid(
            header=header,
            value=int(msg["value"]),
            pubkey=bytes.fromhex(msg["pubkey"].removeprefix("0x")),
            blob_kzg_commitments=(
                [
                    bytes.fromhex(c.removeprefix("0x"))
                    for c in comms
                ]
                if comms is not None
                else None
            ),
        )

    async def submit_blinded_block(self, fork: str, signed_blinded):
        """Reveal: returns the full ExecutionPayload, or (payload,
        blobs_bundle dict) when the relay answers with deneb's
        ExecutionPayloadAndBlobsBundle."""
        from .engine import payload_from_json

        t = self.types.by_fork[fork].SignedBlindedBeaconBlock
        out = await self._call(
            "POST",
            "/eth/v1/builder/blinded_blocks",
            {"signature": "0x" + bytes(signed_blinded.signature).hex(),
             "message_ssz": t.serialize(signed_blinded).hex()},
        )
        data = out["data"]
        if isinstance(data, dict) and "execution_payload" in data:
            payload = payload_from_json(
                self.types, fork, data["execution_payload"]
            )
            bb = data.get("blobs_bundle") or {}

            def _unhex(xs):
                return [
                    bytes.fromhex(x.removeprefix("0x")) for x in xs
                ]

            bundle = {
                "commitments": _unhex(bb.get("commitments", [])),
                "proofs": _unhex(bb.get("proofs", [])),
                "blobs": _unhex(bb.get("blobs", [])),
            }
            return payload, bundle
        return payload_from_json(self.types, fork, data)

    def _header_from_json(self, fork: str, obj: dict):
        from .engine import from_data, from_quantity

        hdr = self.types.by_fork[fork].ExecutionPayloadHeader.default()
        for name, _ in self.types.by_fork[fork].ExecutionPayloadHeader.fields:
            camel = "".join(
                w.capitalize() if i else w
                for i, w in enumerate(name.split("_"))
            )
            if camel not in obj:
                continue
            v = obj[camel]
            if isinstance(v, str) and v.startswith("0x"):
                setattr(hdr, name, from_data(v))
            else:
                setattr(hdr, name, int(v))
        return hdr


class MockRelay:
    """In-process relay double for tests: serves bids built from a
    template payload header and records registrations/submissions.
    With `chain=` the relay builds bids from the chain's own dev
    payload for the slot, so the unblinded block passes the real
    state transition end-to-end (the reveal returns the stashed
    payload the header committed to)."""

    def __init__(
        self, types, fork: str = "bellatrix", value: int = 10**9,
        chain=None,
    ):
        self.types = types
        self.fork = fork
        self.value = value
        self.chain = chain
        self.enabled = True
        self.registrations: list = []
        self.submissions: list = []
        self._payloads: dict[bytes, object] = {}

    async def register_validators(self, registrations) -> None:
        self.registrations.extend(registrations)

    def _header_of(self, fork: str, payload):
        from ..statetransition.block import payload_to_header

        return payload_to_header(self.types.by_fork[fork], payload)

    async def get_header(self, slot, parent_hash, pubkey):
        if self.chain is not None:
            from ..chain.chain import _clone
            from ..statetransition.slot import process_slots

            work = _clone(
                self.chain.get_or_regen_state(self.chain.head_root),
                self.types,
            )
            process_slots(self.chain.cfg, work, int(slot), self.types)
            payload = self.chain._build_dev_payload(work, int(slot))
            self._payloads[bytes(payload.block_hash)] = (
                work.fork, payload
            )
            hdr = self._header_of(work.fork, payload)
            from ..params import ForkSeq

            return BuilderBid(
                header=hdr, value=self.value, pubkey=b"\x00" * 48,
                blob_kzg_commitments=(
                    [] if work.fork_seq >= ForkSeq.deneb else None
                ),
            )
        hdr = self.types.by_fork[self.fork].ExecutionPayloadHeader.default()
        hdr.parent_hash = bytes(parent_hash)
        hdr.block_number = slot
        hdr.block_hash = b"\x42" * 32
        return BuilderBid(header=hdr, value=self.value, pubkey=b"\x00" * 48)

    async def submit_blinded_block(self, fork, signed_blinded):
        self.submissions.append(signed_blinded)
        want = bytes(
            signed_blinded.message.body.execution_payload_header.block_hash
        )
        if want in self._payloads:
            return self._payloads[want][1]
        payload = self.types.by_fork[fork].ExecutionPayload.default()
        payload.block_hash = b"\x42" * 32
        payload.block_number = int(signed_blinded.message.slot)
        return payload

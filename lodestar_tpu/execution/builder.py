"""External block builder (MEV-boost relay) client.

Reference analog: ExecutionBuilderHttp (execution/builder/http.ts:60)
over the builder-specs REST API: registerValidator, getHeader (bid for
a blinded block), submitBlindedBlock (reveal). `MockRelay` is the test
double (reference uses mocked relays in unit tests).

Fault handling mirrors the reference's builder circuit breaker: faults
(relay errors, missed proposals) are recorded per slot into a
`FaultInspectionWindow`; while more than `allowed_faults` slots of the
trailing window carry faults, `available()` is False and the proposal
path skips the builder race entirely, producing locally. The
reference's knobs are the `faultInspectionWindow` / `allowedFaults`
CLI flags; here they are constructor params.
"""

from __future__ import annotations

import asyncio
import json
import urllib.error
import urllib.request
from dataclasses import dataclass

from ..resilience import FaultInspectionWindow


class BuilderError(Exception):
    pass


def unblind_signed_block(ns, signed_blinded, payload):
    """SignedBlindedBeaconBlock + revealed payload -> full
    SignedBeaconBlock. The signature carries over because the blinded
    and full blocks hash to the same root when the header commits to
    the payload (shared by the API unblinding route and the sim's
    builder proposal path)."""
    blinded = signed_blinded.message
    full = ns.SignedBeaconBlock.default()
    msg = full.message
    msg.slot = blinded.slot
    msg.proposer_index = blinded.proposer_index
    msg.parent_root = bytes(blinded.parent_root)
    msg.state_root = bytes(blinded.state_root)
    body = msg.body
    for name, _ in ns.BlindedBeaconBlockBody.fields:
        if name == "execution_payload_header":
            body.execution_payload = payload
        else:
            setattr(body, name, getattr(blinded.body, name))
    full.signature = bytes(signed_blinded.signature)
    return full


def missed_slots_in_window(chain, current_slot: int, window: int) -> int:
    """Count slots in (current_slot - window, current_slot) without a
    canonical block — the reference breaker's fault signal (a relay
    that wins bids and then withholds payloads shows up as missed
    proposals, not client-side errors)."""
    lo = max(0, current_slot - window)
    have = set()
    proto = chain.fork_choice.proto
    for n in proto.iter_chain(chain.head_root):
        if n.slot <= lo:
            break
        have.add(n.slot)
    return sum(
        1 for s in range(lo + 1, current_slot) if s not in have
    )


@dataclass
class BuilderBid:
    header: object  # ExecutionPayloadHeader value
    value: int
    pubkey: bytes
    # deneb+: the bid commits to its blob set (builder-specs
    # BuilderBid.blob_kzg_commitments); None = pre-deneb / not provided
    blob_kzg_commitments: list | None = None


class ExecutionBuilderHttp:
    """builder-specs REST client (http.ts:60) behind a fault-
    inspection-window circuit breaker: relay errors and missed slots
    are recorded per slot; when more than `allowed_faults` of the
    trailing `fault_inspection_window` slots are faulty, `available()`
    goes False (the proposal path then skips the builder race and
    produces locally) until the faults age out and a probe bid
    succeeds."""

    def __init__(self, base_url: str, types, timeout: float = 5.0,
                 fault_inspection_window: int = 32,
                 allowed_faults: int = 4, metrics=None):
        self.base_url = base_url.rstrip("/")
        self.types = types
        self.timeout = timeout
        self.enabled = True  # operator kill-switch, not the breaker
        self.circuit_breaker = FaultInspectionWindow(
            name="builder",
            window=fault_inspection_window,
            allowed_faults=allowed_faults,
        )
        self.metrics = metrics  # resilience family (node wiring)

    # -- breaker bookkeeping (callers know the slot; the HTTP layer
    #    doesn't) -----------------------------------------------------

    def available(self, slot: int) -> bool:
        return self.enabled and self.circuit_breaker.available(slot)

    def register_fault(self, slot: int, kind: str = "relay_error") -> None:
        self.circuit_breaker.record_fault(slot)
        if self.metrics is not None:
            self.metrics.builder_faults_total.inc(kind=kind)

    def register_success(self, slot: int) -> None:
        self.circuit_breaker.record_success(slot)

    async def _call(self, method: str, path: str, body=None):
        if not self.enabled:
            raise BuilderError("builder disabled")

        def _do():
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                self.base_url + path,
                data=data,
                method=method,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else None

        try:
            return await asyncio.get_event_loop().run_in_executor(
                None, _do
            )
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise BuilderError(str(e)) from e

    async def register_validators(self, registrations: list[dict]) -> None:
        await self._call(
            "POST", "/eth/v1/builder/validators", registrations
        )

    async def get_header(
        self, slot: int, parent_hash: bytes, pubkey: bytes
    ) -> BuilderBid | None:
        out = await self._call(
            "GET",
            f"/eth/v1/builder/header/{slot}/0x{parent_hash.hex()}"
            f"/0x{pubkey.hex()}",
        )
        if out is None:
            return None
        msg = out["data"]["message"]
        hdr = msg["header"]
        fork = out.get("version", "bellatrix")
        header = self._header_from_json(fork, hdr)
        comms = msg.get("blob_kzg_commitments")
        return BuilderBid(
            header=header,
            value=int(msg["value"]),
            pubkey=bytes.fromhex(msg["pubkey"].removeprefix("0x")),
            blob_kzg_commitments=(
                [
                    bytes.fromhex(c.removeprefix("0x"))
                    for c in comms
                ]
                if comms is not None
                else None
            ),
        )

    async def submit_blinded_block(self, fork: str, signed_blinded):
        """Reveal: returns the full ExecutionPayload, or (payload,
        blobs_bundle dict) when the relay answers with deneb's
        ExecutionPayloadAndBlobsBundle."""
        from .engine import payload_from_json

        t = self.types.by_fork[fork].SignedBlindedBeaconBlock
        out = await self._call(
            "POST",
            "/eth/v1/builder/blinded_blocks",
            {"signature": "0x" + bytes(signed_blinded.signature).hex(),
             "message_ssz": t.serialize(signed_blinded).hex()},
        )
        data = out["data"]
        if isinstance(data, dict) and "execution_payload" in data:
            payload = payload_from_json(
                self.types, fork, data["execution_payload"]
            )
            bb = data.get("blobs_bundle") or {}

            def _unhex(xs):
                return [
                    bytes.fromhex(x.removeprefix("0x")) for x in xs
                ]

            bundle = {
                "commitments": _unhex(bb.get("commitments", [])),
                "proofs": _unhex(bb.get("proofs", [])),
                "blobs": _unhex(bb.get("blobs", [])),
            }
            return payload, bundle
        return payload_from_json(self.types, fork, data)

    def _header_from_json(self, fork: str, obj: dict):
        from .engine import from_data, from_quantity

        hdr = self.types.by_fork[fork].ExecutionPayloadHeader.default()
        for name, _ in self.types.by_fork[fork].ExecutionPayloadHeader.fields:
            camel = "".join(
                w.capitalize() if i else w
                for i, w in enumerate(name.split("_"))
            )
            if camel not in obj:
                continue
            v = obj[camel]
            if isinstance(v, str) and v.startswith("0x"):
                setattr(hdr, name, from_data(v))
            else:
                setattr(hdr, name, int(v))
        return hdr


class MockRelay:
    """In-process relay double for tests: serves bids built from a
    template payload header and records registrations/submissions.
    With `chain=` the relay builds bids from the chain's own dev
    payload for the slot, so the unblinded block passes the real
    state transition end-to-end (the reveal returns the stashed
    payload the header committed to)."""

    def __init__(
        self, types, fork: str = "bellatrix", value: int = 10**9,
        chain=None,
    ):
        self.types = types
        self.fork = fork
        self.value = value
        self.chain = chain
        self.enabled = True
        self.registrations: list = []
        self.submissions: list = []
        self._payloads: dict[bytes, object] = {}

    async def register_validators(self, registrations) -> None:
        self.registrations.extend(registrations)

    def _header_of(self, fork: str, payload):
        from ..statetransition.block import payload_to_header

        return payload_to_header(self.types.by_fork[fork], payload)

    async def get_header(self, slot, parent_hash, pubkey):
        if self.chain is not None:
            from ..chain.chain import _clone
            from ..statetransition.slot import process_slots

            work = _clone(
                self.chain.get_or_regen_state(self.chain.head_root),
                self.types,
            )
            process_slots(self.chain.cfg, work, int(slot), self.types)
            payload = self.chain._build_dev_payload(work, int(slot))
            self._payloads[bytes(payload.block_hash)] = (
                work.fork, payload
            )
            hdr = self._header_of(work.fork, payload)
            from ..params import ForkSeq

            return BuilderBid(
                header=hdr, value=self.value, pubkey=b"\x00" * 48,
                blob_kzg_commitments=(
                    [] if work.fork_seq >= ForkSeq.deneb else None
                ),
            )
        hdr = self.types.by_fork[self.fork].ExecutionPayloadHeader.default()
        hdr.parent_hash = bytes(parent_hash)
        hdr.block_number = slot
        hdr.block_hash = b"\x42" * 32
        return BuilderBid(header=hdr, value=self.value, pubkey=b"\x00" * 48)

    async def submit_blinded_block(self, fork, signed_blinded):
        self.submissions.append(signed_blinded)
        want = bytes(
            signed_blinded.message.body.execution_payload_header.block_hash
        )
        if want in self._payloads:
            return self._payloads[want][1]
        payload = self.types.by_fork[fork].ExecutionPayload.default()
        payload.block_hash = b"\x42" * 32
        payload.block_number = int(signed_blinded.message.slot)
        return payload

"""Engine API HTTP client: JSON-RPC with JWT (HS256) auth.

Reference analog: ExecutionEngineHttp (execution/engine/http.ts:115) on
top of JsonRpcHttpClient (eth1/provider/jsonRpcHttpClient.ts:76) — the
beacon node's channel to the execution client: engine_newPayloadV1-V3,
engine_forkchoiceUpdatedV1-V3, engine_getPayloadV1-V3,
engine_getPayloadBodiesByHashV1. Method versions follow the fork, as
http.ts:199-256 does. Transport is stdlib urllib driven through the
event loop's executor (same pattern as api/client.py).
"""

from __future__ import annotations

import asyncio
import base64
import hmac
import json
import time
import urllib.error
import urllib.request
from hashlib import sha256

from ..params import ForkSeq
from .engine import (
    ExecutionPayloadStatus,
    ForkchoiceResponse,
    ForkchoiceState,
    GetPayloadResponse,
    PayloadAttributes,
    PayloadStatus,
    data,
    from_data,
    from_quantity,
    payload_from_json,
    payload_to_json,
    quantity,
)


class EngineApiError(Exception):
    pass


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def jwt_token(secret: bytes, now: float | None = None) -> str:
    """HS256 JWT with an `iat` claim — the engine API auth scheme
    (http.ts jwtSecret handling; EL verifies iat within +-60s)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = _b64url(
        json.dumps({"iat": int(now if now is not None else time.time())}).encode()
    )
    signing_input = f"{header}.{claims}".encode()
    sig = hmac.new(secret, signing_input, sha256).digest()
    return f"{header}.{claims}.{_b64url(sig)}"


class JsonRpcHttpClient:
    """Minimal JSON-RPC 2.0 over HTTP with retries + JWT.

    Reference: eth1/provider/jsonRpcHttpClient.ts:76 (retry/timeout/
    metrics wrapper around fetch)."""

    def __init__(
        self,
        url: str,
        jwt_secret: bytes | None = None,
        timeout: float = 12.0,
        retries: int = 1,
    ):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self.retries = retries
        self._id = 0

    def call_sync(self, method: str, params: list):
        self._id += 1
        payload = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": self._id,
                "method": method,
                "params": params,
            }
        ).encode()
        headers = {"Content-Type": "application/json"}
        last = None
        for _ in range(self.retries + 1):
            if self.jwt_secret is not None:
                headers["Authorization"] = (
                    "Bearer " + jwt_token(self.jwt_secret)
                )
            req = urllib.request.Request(
                self.url, data=payload, headers=headers, method="POST"
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout
                ) as resp:
                    out = json.loads(resp.read())
                if "error" in out and out["error"]:
                    raise EngineApiError(
                        f"{method}: {out['error'].get('message')} "
                        f"(code {out['error'].get('code')})"
                    )
                return out.get("result")
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                last = e
        raise EngineApiError(f"{method}: transport failed: {last}")

    async def call(self, method: str, params: list):
        return await asyncio.get_event_loop().run_in_executor(
            None, self.call_sync, method, params
        )


def _status_from_json(obj: dict) -> PayloadStatus:
    return PayloadStatus(
        status=ExecutionPayloadStatus(obj["status"]),
        latest_valid_hash=(
            from_data(obj["latestValidHash"])
            if obj.get("latestValidHash")
            else None
        ),
        validation_error=obj.get("validationError"),
    )


class ExecutionEngineHttp:
    """IExecutionEngine over JSON-RPC (reference: engine/http.ts:115)."""

    def __init__(self, rpc: JsonRpcHttpClient, types=None):
        self.rpc = rpc
        self.types = types

    @classmethod
    def connect(cls, url: str, jwt_secret: bytes | None = None, types=None):
        return cls(JsonRpcHttpClient(url, jwt_secret=jwt_secret), types)

    @staticmethod
    def _new_payload_version(fork_seq: int) -> int:
        if fork_seq >= ForkSeq.electra:
            return 4
        if fork_seq >= ForkSeq.deneb:
            return 3
        if fork_seq >= ForkSeq.capella:
            return 2
        return 1

    async def notify_new_payload(
        self,
        fork: str,
        payload,
        versioned_hashes=None,
        parent_root=None,
        execution_requests=None,
    ) -> PayloadStatus:
        fork_seq = int(ForkSeq[fork])
        v = self._new_payload_version(fork_seq)
        params: list = [payload_to_json(payload, fork_seq)]
        if v >= 3:
            params.append([data(h) for h in (versioned_hashes or [])])
            params.append(data(parent_root or b"\x00" * 32))
        if v >= 4:
            # electra: type-prefixed request blobs (EIP-7685 encoding)
            params.append(
                [data(r) for r in (execution_requests or [])]
            )
        result = await self.rpc.call(f"engine_newPayloadV{v}", params)
        return _status_from_json(result)

    async def notify_forkchoice_update(
        self,
        fork: str,
        state: ForkchoiceState,
        attributes: PayloadAttributes | None = None,
    ) -> ForkchoiceResponse:
        fork_seq = int(ForkSeq[fork])
        v = 3 if fork_seq >= ForkSeq.deneb else (
            2 if fork_seq >= ForkSeq.capella else 1
        )
        fc = {
            "headBlockHash": data(state.head_block_hash),
            "safeBlockHash": data(state.safe_block_hash),
            "finalizedBlockHash": data(state.finalized_block_hash),
        }
        attrs = None
        if attributes is not None:
            attrs = {
                "timestamp": quantity(attributes.timestamp),
                "prevRandao": data(attributes.prev_randao),
                "suggestedFeeRecipient": data(
                    attributes.suggested_fee_recipient
                ),
            }
            if fork_seq >= ForkSeq.capella:
                attrs["withdrawals"] = [
                    {
                        "index": quantity(w.index),
                        "validatorIndex": quantity(w.validator_index),
                        "address": data(w.address),
                        "amount": quantity(w.amount),
                    }
                    for w in (attributes.withdrawals or [])
                ]
            if fork_seq >= ForkSeq.deneb:
                attrs["parentBeaconBlockRoot"] = data(
                    attributes.parent_beacon_block_root or b"\x00" * 32
                )
        result = await self.rpc.call(
            f"engine_forkchoiceUpdatedV{v}", [fc, attrs]
        )
        return ForkchoiceResponse(
            payload_status=_status_from_json(result["payloadStatus"]),
            payload_id=(
                from_data(result["payloadId"])
                if result.get("payloadId")
                else None
            ),
        )

    async def get_payload(
        self, fork: str, payload_id: bytes, types=None
    ) -> GetPayloadResponse:
        types = types if types is not None else self.types
        fork_seq = int(ForkSeq[fork])
        v = self._new_payload_version(fork_seq)
        result = await self.rpc.call(
            f"engine_getPayloadV{v}", [data(payload_id)]
        )
        if v == 1:
            payload_json, value, bundle = result, "0x0", None
        else:
            payload_json = result["executionPayload"]
            value = result.get("blockValue", "0x0")
            bundle = result.get("blobsBundle")
        return GetPayloadResponse(
            execution_payload=payload_from_json(types, fork, payload_json),
            block_value=from_quantity(value),
            blobs_bundle=(
                {
                    "commitments": [
                        from_data(c) for c in bundle["commitments"]
                    ],
                    "proofs": [from_data(p) for p in bundle["proofs"]],
                    "blobs": [from_data(b) for b in bundle["blobs"]],
                }
                if bundle
                else None
            ),
            should_override_builder=bool(
                result.get("shouldOverrideBuilder", False)
            ),
        )

    async def get_payload_bodies_by_hash(self, fork: str, block_hashes):
        return await self.rpc.call(
            "engine_getPayloadBodiesByHashV1",
            [[data(h) for h in block_hashes]],
        )

    async def get_payload_bodies_by_range(self, fork: str, start, count):
        return await self.rpc.call(
            "engine_getPayloadBodiesByRangeV1",
            [quantity(start), quantity(count)],
        )

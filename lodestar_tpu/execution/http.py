"""Engine API HTTP client: JSON-RPC with JWT (HS256) auth.

Reference analog: ExecutionEngineHttp (execution/engine/http.ts:115) on
top of JsonRpcHttpClient (eth1/provider/jsonRpcHttpClient.ts:76) — the
beacon node's channel to the execution client: engine_newPayloadV1-V3,
engine_forkchoiceUpdatedV1-V3, engine_getPayloadV1-V3,
engine_getPayloadBodiesByHashV1. Method versions follow the fork, as
http.ts:199-256 does. Transport is stdlib urllib driven through the
event loop's executor (same pattern as api/client.py).
"""

from __future__ import annotations

import asyncio
import base64
import hmac
import json
import time
import urllib.error
import urllib.request
from hashlib import sha256

from ..params import ForkSeq
from ..resilience import RetryOptions, retry, retry_sync
from .engine import (
    ExecutionEngineError,
    ExecutionPayloadStatus,
    ForkchoiceResponse,
    ForkchoiceState,
    GetPayloadResponse,
    PayloadAttributes,
    PayloadStatus,
    data,
    from_data,
    from_quantity,
    payload_from_json,
    payload_to_json,
    quantity,
)


class EngineApiError(ExecutionEngineError):
    pass


class RpcTransportError(EngineApiError):
    """The wire failed (refused/reset/timeout) — worth retrying."""

    retryable = True


class EngineRpcError(EngineApiError):
    """The server ANSWERED with a JSON-RPC error object. The call was
    delivered; retrying the identical request cannot change the
    verdict (jsonRpcHttpClient.ts treats these as terminal too).
    `answered = True` tells the availability layer the engine is
    reachable — an error answer must not open the circuit breaker or
    mark the engine OFFLINE."""

    retryable = False
    answered = True

    def __init__(self, method: str, message, code):
        super().__init__(f"{method}: {message} (code {code})")
        self.code = code


class EngineAuthError(EngineApiError):
    """HTTP 401/403 — JWT rejected. Never retried; drives the
    AUTH_FAILED engine state."""

    retryable = False
    auth_failed = True


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def jwt_token(secret: bytes, now: float | None = None) -> str:
    """HS256 JWT with an `iat` claim — the engine API auth scheme
    (http.ts jwtSecret handling; EL verifies iat within +-60s)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = _b64url(
        json.dumps({"iat": int(now if now is not None else time.time())}).encode()
    )
    signing_input = f"{header}.{claims}".encode()
    sig = hmac.new(secret, signing_input, sha256).digest()
    return f"{header}.{claims}.{_b64url(sig)}"


class JsonRpcHttpClient:
    """JSON-RPC 2.0 over HTTP with classified retries + JWT.

    Reference: eth1/provider/jsonRpcHttpClient.ts:76 (retry/timeout/
    metrics wrapper around fetch). Retry policy: transport failures
    (refused/reset/per-attempt timeout) are retried with capped
    exponential backoff + full jitter; JSON-RPC error responses and
    auth rejections are terminal. The clock/rng are injectable so the
    retry schedule is unit-testable without sleeping."""

    def __init__(
        self,
        url: str,
        jwt_secret: bytes | None = None,
        timeout: float = 12.0,
        retries: int = 1,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        clock=None,
        rng=None,
        name: str = "engine",
        metrics=None,  # resilience metric family (node wiring)
    ):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self.retries = retries
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.clock = clock
        self.rng = rng
        self.name = name
        self.metrics = metrics
        self._id = 0

    def _request_once(self, method: str, payload: bytes):
        """One HTTP exchange; raises the classified error family."""
        headers = {"Content-Type": "application/json"}
        if self.jwt_secret is not None:
            headers["Authorization"] = (
                "Bearer " + jwt_token(self.jwt_secret)
            )
        req = urllib.request.Request(
            self.url, data=payload, headers=headers, method="POST"
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout
            ) as resp:
                try:
                    out = json.loads(resp.read())
                except (ValueError, OSError) as e:
                    # HTTP 200 with a non-JSON/truncated body (proxy
                    # error page, cut connection): transport-shaped,
                    # retryable — must stay inside the error taxonomy
                    # so chain-side degradation matches it
                    raise RpcTransportError(
                        f"{method}: malformed response body: {e}"
                    ) from e
        except urllib.error.HTTPError as e:
            if e.code in (401, 403):
                raise EngineAuthError(
                    f"{method}: auth rejected (HTTP {e.code})"
                ) from e
            raise RpcTransportError(
                f"{method}: HTTP {e.code}"
            ) from e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            raise RpcTransportError(
                f"{method}: transport failed: {e}"
            ) from e
        if "error" in out and out["error"]:
            raise EngineRpcError(
                method,
                out["error"].get("message"),
                out["error"].get("code"),
            )
        return out.get("result")

    def _payload_for(self, method: str, params: list) -> bytes:
        self._id += 1
        return json.dumps(
            {
                "jsonrpc": "2.0",
                "id": self._id,
                "method": method,
                "params": params,
            }
        ).encode()

    def _retry_opts(self) -> RetryOptions:
        opts = RetryOptions(
            retries=self.retries,
            base_delay=self.base_delay,
            max_delay=self.max_delay,
        )
        if self.metrics is not None:
            from ..resilience import make_retry_hook

            opts.on_retry = make_retry_hook(self.metrics, self.name)
        return opts

    def _count_giveup(self, exc) -> None:
        # "gave up" means retries were actually exhausted — terminal
        # first-attempt answers (RPC error objects, auth rejections)
        # were never retried and must not inflate the counter
        if self.metrics is not None and getattr(
            exc, "retryable", False
        ):
            self.metrics.retry_giveups_total.inc(client=self.name)

    def call_sync(self, method: str, params: list):
        payload = self._payload_for(method, params)
        try:
            return retry_sync(
                lambda: self._request_once(method, payload),
                self._retry_opts(),
                clock=self.clock,
                rng=self.rng,
            )
        except EngineApiError as e:
            self._count_giveup(e)
            raise

    async def call(self, method: str, params: list):
        """Async path: each attempt runs the blocking exchange in the
        executor; backoff sleeps ride the (injectable) async clock so
        the event loop is never blocked between attempts."""
        payload = self._payload_for(method, params)
        loop = asyncio.get_event_loop()

        def attempt():
            return loop.run_in_executor(
                None, self._request_once, method, payload
            )

        try:
            return await retry(
                attempt,
                self._retry_opts(),
                clock=self.clock,
                rng=self.rng,
            )
        except EngineApiError as e:
            self._count_giveup(e)
            raise


def _status_from_json(obj: dict) -> PayloadStatus:
    return PayloadStatus(
        status=ExecutionPayloadStatus(obj["status"]),
        latest_valid_hash=(
            from_data(obj["latestValidHash"])
            if obj.get("latestValidHash")
            else None
        ),
        validation_error=obj.get("validationError"),
    )


class ExecutionEngineHttp:
    """IExecutionEngine over JSON-RPC (reference: engine/http.ts:115)."""

    def __init__(self, rpc: JsonRpcHttpClient, types=None):
        self.rpc = rpc
        self.types = types

    @classmethod
    def connect(cls, url: str, jwt_secret: bytes | None = None, types=None):
        return cls(JsonRpcHttpClient(url, jwt_secret=jwt_secret), types)

    @staticmethod
    def _new_payload_version(fork_seq: int) -> int:
        if fork_seq >= ForkSeq.electra:
            return 4
        if fork_seq >= ForkSeq.deneb:
            return 3
        if fork_seq >= ForkSeq.capella:
            return 2
        return 1

    async def notify_new_payload(
        self,
        fork: str,
        payload,
        versioned_hashes=None,
        parent_root=None,
        execution_requests=None,
    ) -> PayloadStatus:
        fork_seq = int(ForkSeq[fork])
        v = self._new_payload_version(fork_seq)
        params: list = [payload_to_json(payload, fork_seq)]
        if v >= 3:
            params.append([data(h) for h in (versioned_hashes or [])])
            params.append(data(parent_root or b"\x00" * 32))
        if v >= 4:
            # electra: type-prefixed request blobs (EIP-7685 encoding)
            params.append(
                [data(r) for r in (execution_requests or [])]
            )
        result = await self.rpc.call(f"engine_newPayloadV{v}", params)
        return _status_from_json(result)

    async def notify_forkchoice_update(
        self,
        fork: str,
        state: ForkchoiceState,
        attributes: PayloadAttributes | None = None,
    ) -> ForkchoiceResponse:
        fork_seq = int(ForkSeq[fork])
        v = 3 if fork_seq >= ForkSeq.deneb else (
            2 if fork_seq >= ForkSeq.capella else 1
        )
        fc = {
            "headBlockHash": data(state.head_block_hash),
            "safeBlockHash": data(state.safe_block_hash),
            "finalizedBlockHash": data(state.finalized_block_hash),
        }
        attrs = None
        if attributes is not None:
            attrs = {
                "timestamp": quantity(attributes.timestamp),
                "prevRandao": data(attributes.prev_randao),
                "suggestedFeeRecipient": data(
                    attributes.suggested_fee_recipient
                ),
            }
            if fork_seq >= ForkSeq.capella:
                attrs["withdrawals"] = [
                    {
                        "index": quantity(w.index),
                        "validatorIndex": quantity(w.validator_index),
                        "address": data(w.address),
                        "amount": quantity(w.amount),
                    }
                    for w in (attributes.withdrawals or [])
                ]
            if fork_seq >= ForkSeq.deneb:
                attrs["parentBeaconBlockRoot"] = data(
                    attributes.parent_beacon_block_root or b"\x00" * 32
                )
        result = await self.rpc.call(
            f"engine_forkchoiceUpdatedV{v}", [fc, attrs]
        )
        return ForkchoiceResponse(
            payload_status=_status_from_json(result["payloadStatus"]),
            payload_id=(
                from_data(result["payloadId"])
                if result.get("payloadId")
                else None
            ),
        )

    async def get_payload(
        self, fork: str, payload_id: bytes, types=None
    ) -> GetPayloadResponse:
        types = types if types is not None else self.types
        fork_seq = int(ForkSeq[fork])
        v = self._new_payload_version(fork_seq)
        result = await self.rpc.call(
            f"engine_getPayloadV{v}", [data(payload_id)]
        )
        if v == 1:
            payload_json, value, bundle = result, "0x0", None
        else:
            payload_json = result["executionPayload"]
            value = result.get("blockValue", "0x0")
            bundle = result.get("blobsBundle")
        return GetPayloadResponse(
            execution_payload=payload_from_json(types, fork, payload_json),
            block_value=from_quantity(value),
            blobs_bundle=(
                {
                    "commitments": [
                        from_data(c) for c in bundle["commitments"]
                    ],
                    "proofs": [from_data(p) for p in bundle["proofs"]],
                    "blobs": [from_data(b) for b in bundle["blobs"]],
                }
                if bundle
                else None
            ),
            should_override_builder=bool(
                result.get("shouldOverrideBuilder", False)
            ),
        )

    async def get_payload_bodies_by_hash(self, fork: str, block_hashes):
        return await self.rpc.call(
            "engine_getPayloadBodiesByHashV1",
            [[data(h) for h in block_hashes]],
        )

    async def get_payload_bodies_by_range(self, fork: str, start, count):
        return await self.rpc.call(
            "engine_getPayloadBodiesByRangeV1",
            [quantity(start), quantity(count)],
        )

"""Engine API types + payload <-> JSON codecs.

Reference analog: execution/engine/interface.ts (IExecutionEngine,
ExecutePayloadStatus at interface.ts:23-60) and the serializers in
engine/types.ts. The JSON forms follow the Engine API spec: QUANTITY
as 0x-hex without leading zeros, DATA as 0x-hex bytes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum


class ExecutionEngineError(Exception):
    """Base for engine-availability failures (transport, auth, circuit
    open). Chain import treats these as 'engine unreachable' and falls
    back to optimistic handling instead of crashing the import."""

    retryable = True
    auth_failed = False


class EngineOfflineError(ExecutionEngineError):
    """Fail-fast signal: the engine breaker is OPEN, no call was made."""

    retryable = False


class ExecutionPayloadStatus(str, Enum):
    """engine_newPayload verdicts (interface.ts:23-60)."""

    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"
    INVALID_BLOCK_HASH = "INVALID_BLOCK_HASH"
    ELERROR = "ELERROR"  # client-side: EL unreachable/errored
    UNAVAILABLE = "UNAVAILABLE"


@dataclass
class PayloadStatus:
    status: ExecutionPayloadStatus
    latest_valid_hash: bytes | None = None
    validation_error: str | None = None


@dataclass
class ForkchoiceState:
    head_block_hash: bytes
    safe_block_hash: bytes
    finalized_block_hash: bytes


@dataclass
class PayloadAttributes:
    timestamp: int
    prev_randao: bytes
    suggested_fee_recipient: bytes
    withdrawals: list | None = None  # capella+
    parent_beacon_block_root: bytes | None = None  # deneb+


@dataclass
class ForkchoiceResponse:
    payload_status: PayloadStatus
    payload_id: bytes | None = None


@dataclass
class GetPayloadResponse:
    execution_payload: object  # SSZ ExecutionPayload value
    block_value: int = 0
    blobs_bundle: dict | None = None  # {commitments, proofs, blobs}
    should_override_builder: bool = False


# ---------------------------------------------------------------------------
# JSON codecs (Engine API wire form)
# ---------------------------------------------------------------------------


def quantity(n: int) -> str:
    return hex(int(n))


def data(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def from_quantity(s: str) -> int:
    return int(s, 16)


def from_data(s: str) -> bytes:
    return bytes.fromhex(s.removeprefix("0x"))


def payload_to_json(payload, fork_seq: int) -> dict:
    """SSZ ExecutionPayload value -> engine API ExecutionPayloadV1/2/3."""
    from ..params import ForkSeq

    out = {
        "parentHash": data(payload.parent_hash),
        "feeRecipient": data(payload.fee_recipient),
        "stateRoot": data(payload.state_root),
        "receiptsRoot": data(payload.receipts_root),
        "logsBloom": data(payload.logs_bloom),
        "prevRandao": data(payload.prev_randao),
        "blockNumber": quantity(payload.block_number),
        "gasLimit": quantity(payload.gas_limit),
        "gasUsed": quantity(payload.gas_used),
        "timestamp": quantity(payload.timestamp),
        "extraData": data(payload.extra_data),
        "baseFeePerGas": quantity(payload.base_fee_per_gas),
        "blockHash": data(payload.block_hash),
        "transactions": [data(tx) for tx in payload.transactions],
    }
    if fork_seq >= ForkSeq.capella:
        out["withdrawals"] = [
            {
                "index": quantity(w.index),
                "validatorIndex": quantity(w.validator_index),
                "address": data(w.address),
                "amount": quantity(w.amount),
            }
            for w in payload.withdrawals
        ]
    if fork_seq >= ForkSeq.deneb:
        out["blobGasUsed"] = quantity(payload.blob_gas_used)
        out["excessBlobGas"] = quantity(payload.excess_blob_gas)
    return out


def payload_from_json(types, fork: str, obj: dict):
    """engine API ExecutionPayloadV* -> SSZ value of the fork's type."""
    from ..params import ForkSeq

    fork_seq = int(ForkSeq[fork])
    payload = types.by_fork[fork].ExecutionPayload.default()
    payload.parent_hash = from_data(obj["parentHash"])
    payload.fee_recipient = from_data(obj["feeRecipient"])
    payload.state_root = from_data(obj["stateRoot"])
    payload.receipts_root = from_data(obj["receiptsRoot"])
    payload.logs_bloom = from_data(obj["logsBloom"])
    payload.prev_randao = from_data(obj["prevRandao"])
    payload.block_number = from_quantity(obj["blockNumber"])
    payload.gas_limit = from_quantity(obj["gasLimit"])
    payload.gas_used = from_quantity(obj["gasUsed"])
    payload.timestamp = from_quantity(obj["timestamp"])
    payload.extra_data = from_data(obj["extraData"])
    payload.base_fee_per_gas = from_quantity(obj["baseFeePerGas"])
    payload.block_hash = from_data(obj["blockHash"])
    payload.transactions = [from_data(tx) for tx in obj["transactions"]]
    if fork_seq >= ForkSeq.capella:
        ws = []
        for w in obj.get("withdrawals") or []:
            wd = types.Withdrawal.default()
            wd.index = from_quantity(w["index"])
            wd.validator_index = from_quantity(w["validatorIndex"])
            wd.address = from_data(w["address"])
            wd.amount = from_quantity(w["amount"])
            ws.append(wd)
        payload.withdrawals = ws
    if fork_seq >= ForkSeq.deneb:
        payload.blob_gas_used = from_quantity(obj.get("blobGasUsed", "0x0"))
        payload.excess_blob_gas = from_quantity(
            obj.get("excessBlobGas", "0x0")
        )
    return payload


# ---------------------------------------------------------------------------
# Availability wrapper
# ---------------------------------------------------------------------------


class ResilientEngine:
    """IExecutionEngine wrapper adding engine-state tracking and a
    fail-fast circuit breaker around ANY inner engine (HTTP client,
    in-process mock, or a sim fault injector).

    Reference analog: the updateEngineState bookkeeping inside
    ExecutionEngineHttp (engine/http.ts) — every exchange drives the
    ONLINE/SYNCED/SYNCING/OFFLINE/AUTH_FAILED machine. On top of that,
    when the breaker is OPEN (the engine has been failing and its
    reset window hasn't elapsed) calls raise EngineOfflineError
    immediately instead of burning a transport timeout per call — the
    fail-fast the block-import and proposal hot paths need while the
    EL is down.
    """

    def __init__(self, inner, tracker=None, breaker=None):
        from ..resilience import CircuitBreaker, EngineStateTracker

        self.inner = inner
        self.tracker = tracker if tracker is not None else (
            EngineStateTracker()
        )
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            name="engine", failure_threshold=3, reset_timeout=12.0
        )

    @property
    def state(self):
        return self.tracker.state

    async def _guarded(self, coro_fn, status_of=None):
        if not self.breaker.allows():
            raise EngineOfflineError(
                "execution engine offline (circuit open, state "
                f"{self.tracker.state.value})"
            )
        try:
            result = await coro_fn()
        except Exception as e:
            if getattr(e, "answered", False):
                # the engine RESPONDED (JSON-RPC error object): it is
                # reachable — availability-wise this is a success, the
                # caller still sees the error
                self.breaker.on_success()
                self.tracker.on_success(None)
            else:
                self.tracker.on_error(e)
                self.breaker.on_failure()
            raise
        except BaseException:
            # cancellation (proposal deadline, shutdown): no verdict on
            # engine health either way, but a half-open probe slot must
            # be handed back or the breaker would deny calls forever
            self.breaker.release_probe()
            raise
        self.breaker.on_success()
        self.tracker.on_success(
            status_of(result) if status_of is not None else None
        )
        return result

    async def notify_new_payload(self, fork, payload, **kw):
        return await self._guarded(
            lambda: self.inner.notify_new_payload(fork, payload, **kw),
            status_of=lambda r: r.status,
        )

    async def notify_forkchoice_update(self, fork, state, attributes=None):
        return await self._guarded(
            lambda: self.inner.notify_forkchoice_update(
                fork, state, attributes
            ),
            status_of=lambda r: r.payload_status.status,
        )

    async def get_payload(self, fork, payload_id, *a, **kw):
        return await self._guarded(
            lambda: self.inner.get_payload(fork, payload_id, *a, **kw)
        )

    async def get_payload_bodies_by_hash(self, fork, block_hashes):
        return await self._guarded(
            lambda: self.inner.get_payload_bodies_by_hash(
                fork, block_hashes
            )
        )

    async def get_payload_bodies_by_range(self, fork, start, count):
        return await self._guarded(
            lambda: self.inner.get_payload_bodies_by_range(
                fork, start, count
            )
        )

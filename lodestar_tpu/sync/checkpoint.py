"""Checkpoint-sync bootstrapping: fetch a finalized state from a
trusted beacon API and anchor the chain on it.

Reference analog: initBeaconState / fetchWeakSubjectivityState
(cli/src/cmds/beacon/initBeaconState.ts): download the finalized state
from a trusted REST endpoint, validate it, start the chain from that
anchor, and let BackfillSync fill history backwards. The transport is
this repo's getStateV2 debug route (SSZ hex in JSON — see
api/impl.py get_state_v2).
"""

from __future__ import annotations

import time

from ..statetransition.slot import BeaconStateView, fork_at_epoch


class CheckpointSyncError(Exception):
    pass


def fetch_checkpoint_state(
    url: str,
    cfg,
    types,
    state_id: str = "finalized",
    expected_root: bytes | None = None,
    now: float | None = None,
    retries: int = 2,
    clock=None,
    rng=None,
) -> BeaconStateView:
    """Download + validate a trusted anchor state.

    Validation (initBeaconState.ts wss checks, simplified):
    - the advertised fork must match the config's fork at the state's
      epoch (guards against wrong-network endpoints);
    - the state's clock position must not be in the future;
    - when `expected_root` (a user-supplied weak-subjectivity state
      root) is given, the downloaded state's hashTreeRoot must match.

    The download retries transport failures with backoff (the anchor
    endpoint is a remote dependency like any other); validation
    failures are terminal — a wrong-network or tampered state does not
    become right on re-download.
    """
    from ..api.client import ApiClient
    from ..params import preset
    from ..resilience import RetryOptions, retry_sync

    client = ApiClient(url)
    got = retry_sync(
        lambda: client.call("getStateV2", {"state_id": state_id}),
        RetryOptions(
            retries=retries,
            base_delay=0.5,
            max_delay=10.0,
            # the API client surfaces transport failures and 5xx as
            # ApiError(status>=500); 4xx verdicts (bad state_id, wrong
            # route) are terminal
            retryable=lambda e: (
                isinstance(e, (OSError, TimeoutError))
                or getattr(e, "status", 0) >= 500
            ),
        ),
        clock=clock,
        rng=rng,
    )
    fork = got["version"]
    raw = bytes.fromhex(got["data_ssz"])
    try:
        t = types.by_fork[fork].BeaconState
    except KeyError:
        raise CheckpointSyncError(f"unknown fork {fork!r}") from None
    try:
        state = t.deserialize(raw)
    except Exception as e:
        raise CheckpointSyncError(f"undecodable state: {e!r}") from e

    epoch = int(state.slot) // preset().SLOTS_PER_EPOCH
    want_fork = fork_at_epoch(cfg, epoch)
    if fork != want_fork:
        raise CheckpointSyncError(
            f"fork mismatch: endpoint says {fork}, config expects "
            f"{want_fork} at epoch {epoch} — wrong network?"
        )
    wall = now if now is not None else time.time()
    state_time = int(state.genesis_time) + int(state.slot) * int(
        cfg.SECONDS_PER_SLOT
    )
    if state_time > wall + cfg.SECONDS_PER_SLOT:
        raise CheckpointSyncError(
            "anchor state is from the future — endpoint clock or "
            "network mismatch"
        )
    view = BeaconStateView(state=state, fork=fork)
    if expected_root is not None:
        got_root = view.hash_tree_root(types)
        if bytes(got_root) != bytes(expected_root):
            raise CheckpointSyncError(
                "weak-subjectivity root mismatch: downloaded state "
                f"root {bytes(got_root).hex()[:16]} != expected "
                f"{bytes(expected_root).hex()[:16]}"
            )
    return view

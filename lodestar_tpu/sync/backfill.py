"""Backfill sync: fill history backwards from a checkpoint anchor.

Reference analog: BackfillSync (sync/backfill/backfill.ts:103) +
verifyBlockSequence/verifyBlockProposerSignature (backfill/verify.ts).
A checkpoint-synced node starts from a finalized anchor state and has
no history; backfill walks BACKWARD, downloading ranges and verifying
(a) hash linkage up to the trusted anchor root and (b) proposer
signatures in bulk through the batch verifier — no state transition.
Verified blocks land in the block archive; completed spans are recorded
in the backfilled_ranges bucket for restart resumability.
"""

from __future__ import annotations

from ..bls.api import SignatureSet
from ..config.beacon_config import compute_signing_root_from_roots
from ..network import reqresp as rr
from ..network.wire_types import BeaconBlocksByRangeRequest
from ..params import DOMAIN_BEACON_PROPOSER, preset

BACKFILL_BATCH_SLOTS = 64  # backfill.ts batch sizing


class BackfillError(Exception):
    pass


class BackfillSync:
    """Backward history fill below the chain's anchor."""

    def __init__(self, chain, beacon_cfg, types, node: rr.ReqResp, verifier):
        self.chain = chain
        self.beacon_cfg = beacon_cfg
        self.types = types
        self.node = node
        self.verifier = verifier
        self.peers: list[str] = []
        self.blocks_backfilled = 0

    def add_peer(self, peer_id: str) -> None:
        if peer_id not in self.peers:
            self.peers.append(peer_id)

    async def run(
        self, anchor_parent_root: bytes, anchor_slot: int, to_slot: int = 1
    ):
        """Fill slots [to_slot, anchor_slot) below a trusted anchor
        block: `anchor_parent_root` is the anchor block's parent_root
        (the newest backfilled block must hash to it) and `anchor_slot`
        the anchor block's slot."""
        expected_root = anchor_parent_root
        hi = anchor_slot  # exclusive upper bound of the next batch
        while hi > to_slot:
            lo = max(to_slot, hi - BACKFILL_BATCH_SLOTS)
            blocks = await self._download(lo, hi - lo)
            if not blocks:
                # [lo, hi) can legitimately hold only skipped slots:
                # widen the window downward before giving up
                if lo == to_slot:
                    raise BackfillError(
                        f"no blocks served for [{lo},{hi})"
                    )
                hi = lo
                continue
            expected_root = await self._verify_and_store(
                blocks, expected_root
            )
            hi = int(blocks[0][1].message.slot)
            if self.chain.db is not None:
                self.chain.db.meta.put_int("backfilled_to", hi)
        return self.blocks_backfilled

    async def _download(self, start: int, count: int):
        from .range_sync import decode_block_chunks

        req = BeaconBlocksByRangeRequest(
            start_slot=start, count=count, step=1
        )
        payload = BeaconBlocksByRangeRequest.serialize(req)
        last_err = None
        any_ok = False
        for peer in list(self.peers):
            try:
                chunks = await self.node.request(
                    peer, rr.PROTOCOL_BLOCKS_BY_RANGE, payload
                )
            except (rr.ReqRespError, TimeoutError) as e:
                last_err = e
                continue
            any_ok = True
            if not chunks:
                continue  # peer may lack this span: try the next one
            return decode_block_chunks(self.beacon_cfg, self.types, chunks)
        if not any_ok:
            raise BackfillError(f"all peers failed: {last_err}")
        return []  # every responding peer served an empty span

    async def _verify_and_store(self, blocks, expected_root: bytes) -> bytes:
        """Check hash linkage child->parent against expected_root, then
        batch-verify proposer signatures (backfill/verify.ts). Returns
        the parent root the next (older) batch must end at."""
        types = self.types
        # linkage: walk newest -> oldest
        anchor_state = self.chain.get_or_regen_state(
            self.chain.head_root
        ).state
        sets = []
        for fork, block in reversed(blocks):
            root = types.by_fork[fork].BeaconBlock.hash_tree_root(
                block.message
            )
            if root != expected_root:
                raise BackfillError(
                    f"linkage broken at slot {int(block.message.slot)}: "
                    f"got {root.hex()[:16]}, want {expected_root.hex()[:16]}"
                )
            expected_root = bytes(block.message.parent_root)
            proposer = anchor_state.validators[
                int(block.message.proposer_index)
            ]
            epoch = int(block.message.slot) // preset().SLOTS_PER_EPOCH
            # full fork schedule, not the anchor state's two versions:
            # histories span many forks (backfill/verify.ts)
            domain = self.beacon_cfg.get_domain(
                DOMAIN_BEACON_PROPOSER, epoch
            )
            sets.append(
                SignatureSet(
                    bytes(proposer.pubkey),
                    compute_signing_root_from_roots(root, domain),
                    bytes(block.signature),
                )
            )
        if not await self.verifier.verify_signature_sets(sets):
            raise BackfillError("proposer signature batch failed")
        if self.chain.db is not None:
            for fork, block in blocks:
                self.chain.db.block_archive.put(
                    int(block.message.slot), (fork, block)
                )
        self.blocks_backfilled += len(blocks)
        return expected_root

"""Range sync: epoch-batch download + batched-verify import.

Reference analog: sync/range/chain.ts:78 (SyncChain), batch.ts:62
(Batch state machine: AwaitingDownload -> Downloading -> AwaitingProcess
-> Processing -> AwaitingValidation, with retry + peer replacement on
failure), peerBalancer.ts:10. Downloads go through the reqresp
BeaconBlocksByRange protocol; imports run the chain's full pipeline, so
each batch's signatures hit the TPU verifier as bulk sets — the
reference's "~8,000 sigs per 64-block batch" shape (BASELINE.md).

`SyncServer` is the serving side: the reqresp handlers a node registers
so peers can sync from it (network/reqresp/handlers/*.ts).
"""

from __future__ import annotations

import asyncio
from enum import Enum

from ..network import reqresp as rr
from ..network.wire_types import (
    BeaconBlocksByRangeRequest,
    Status,
)
from ..params import preset

EPOCHS_PER_BATCH = 2  # range/batch.ts EPOCHS_PER_BATCH
MAX_BATCH_DOWNLOAD_ATTEMPTS = 5
MAX_BATCH_PROCESSING_ATTEMPTS = 3

# peer scoring (score.ts simplified): batch failures downscore, enough
# of them remove the peer from the rotation entirely. The floor allows
# MORE failures than one batch's retry budget
# (MAX_BATCH_DOWNLOAD_ATTEMPTS = 5), so a single flaky batch against a
# lone peer can exhaust its retries without banning the peer.
PEER_SCORE_BATCH_FAILURE = -10
PEER_SCORE_SUCCESS = 1
PEER_SCORE_MIN = -60
# backoff between batch retry attempts (seconds; full jitter)
BATCH_RETRY_BASE_DELAY = 0.05
BATCH_RETRY_MAX_DELAY = 2.0


def decode_block_chunks(beacon_cfg, types, chunks):
    """reqresp response chunks -> [(fork, SignedBeaconBlock)] using the
    per-chunk fork-digest context (shared by range/backfill/unknown
    sync)."""
    out = []
    for ch in chunks:
        fork = beacon_cfg.fork_name_from_digest(ch.context)
        out.append(
            (
                fork,
                types.by_fork[fork].SignedBeaconBlock.deserialize(
                    ch.payload
                ),
            )
        )
    return out


class BatchStatus(str, Enum):
    awaiting_download = "AwaitingDownload"
    downloading = "Downloading"
    awaiting_process = "AwaitingProcess"
    processing = "Processing"
    done = "Done"
    failed = "Failed"


class Batch:
    """One EPOCHS_PER_BATCH slot span (batch.ts:62)."""

    def __init__(self, start_slot: int, count: int):
        self.start_slot = start_slot
        self.count = count
        self.status = BatchStatus.awaiting_download
        self.blocks: list = []
        self.blobs_by_root: dict[bytes, list] = {}
        self.download_attempts = 0
        self.processing_attempts = 0
        self.failed_peers: set[str] = set()


class SyncServer:
    """Server-side reqresp handlers backed by a chain + db.

    Protocol coverage mirrors the reference table (protocols.ts:7-95):
    Status, Goodbye, Ping, Metadata v2, BeaconBlocksByRange/Root,
    BlobSidecarsByRange/Root, LightClientBootstrap / FinalityUpdate /
    OptimisticUpdate / UpdatesByRange.
    """

    def __init__(self, chain, beacon_cfg, types, metadata_fn=None):
        self.chain = chain
        self.beacon_cfg = beacon_cfg
        self.types = types
        # metadata_fn() -> (seq_number, attnets set[int], syncnets
        # set[int]); the network facade supplies the live subnet state
        self.metadata_fn = metadata_fn
        self.goodbyes_received: list[tuple[str, int]] = []

    def register(self, node: rr.ReqResp) -> None:
        node.register_handler(rr.PROTOCOL_STATUS, self.on_status)
        node.register_handler(rr.PROTOCOL_GOODBYE, self.on_goodbye)
        node.register_handler(rr.PROTOCOL_PING, self.on_ping)
        node.register_handler(rr.PROTOCOL_METADATA, self.on_metadata)
        node.register_handler(
            rr.PROTOCOL_BLOCKS_BY_RANGE, self.on_blocks_by_range
        )
        node.register_handler(
            rr.PROTOCOL_BLOCKS_BY_ROOT, self.on_blocks_by_root
        )
        node.register_handler(
            rr.PROTOCOL_BLOB_SIDECARS_BY_RANGE,
            self.on_blob_sidecars_by_range,
        )
        node.register_handler(
            rr.PROTOCOL_BLOB_SIDECARS_BY_ROOT,
            self.on_blob_sidecars_by_root,
        )
        node.register_handler(
            rr.PROTOCOL_LC_BOOTSTRAP, self.on_lc_bootstrap
        )
        node.register_handler(
            rr.PROTOCOL_LC_FINALITY_UPDATE, self.on_lc_finality_update
        )
        node.register_handler(
            rr.PROTOCOL_LC_OPTIMISTIC_UPDATE,
            self.on_lc_optimistic_update,
        )
        node.register_handler(
            rr.PROTOCOL_LC_UPDATES_BY_RANGE, self.on_lc_updates_by_range
        )

    def local_status(self):
        chain = self.chain
        head = chain.fork_choice.proto.get_node(chain.head_root)
        fin = chain.finalized_checkpoint
        head_epoch = (head.slot if head else 0) // preset().SLOTS_PER_EPOCH
        st = Status(
            fork_digest=self.beacon_cfg.fork_digest(head_epoch),
            finalized_root=fin.root,
            finalized_epoch=fin.epoch,
            head_root=chain.head_root,
            head_slot=head.slot if head else 0,
        )
        return st

    async def on_status(self, peer, payload):
        yield (b"", Status.serialize(self.local_status()))

    async def on_goodbye(self, peer, payload):
        from ..ssz import uint64

        self.goodbyes_received.append(
            (peer, int(uint64.deserialize(payload)))
        )
        yield (b"", uint64.serialize(0))

    async def on_ping(self, peer, payload):
        from ..ssz import uint64

        yield (b"", uint64.serialize(0))

    async def on_metadata(self, peer, payload):
        """Serve local metadata v2 (handlers, metadata.ts:34)."""
        from ..network.wire_types import Metadata

        seq, attnets, syncnets = (
            self.metadata_fn() if self.metadata_fn else (0, set(), set())
        )
        md = Metadata.default()
        md.seq_number = seq
        for i in attnets:
            md.attnets[i] = True
        for i in syncnets:
            md.syncnets[i] = True
        yield (b"", Metadata.serialize(md))

    def _blobs_for_root(self, block_root: bytes):
        if self.chain.db is None:
            return None
        return self.chain.db.blob_sidecars.get(block_root)

    async def on_blob_sidecars_by_range(self, peer, payload):
        """Stream sidecars of canonical deneb+ blocks in slot-then-index
        order (handlers/blobSidecarsByRange.ts)."""
        from ..network.wire_types import BlobSidecarsByRangeRequest

        req = BlobSidecarsByRangeRequest.deserialize(payload)
        start = int(req.start_slot)
        count = min(int(req.count), rr.MAX_REQUEST_BLOCKS)
        chain = self.chain
        spe = preset().SLOTS_PER_EPOCH
        roots_by_slot: dict[int, bytes] = {}
        for n in chain.fork_choice.proto.iter_chain(chain.head_root):
            if start <= n.slot < start + count:
                roots_by_slot[n.slot] = n.block_root
        if chain.db is not None:
            for slot, (fork, block) in chain.db.block_archive.entries(
                start=start, end=start + count
            ):
                ns = self.types.by_fork[fork]
                roots_by_slot.setdefault(
                    slot, ns.BeaconBlock.hash_tree_root(block.message)
                )
        from ..network.wire_types import MAX_REQUEST_BLOB_SIDECARS

        served = 0
        for slot in sorted(roots_by_slot):
            got = self._blobs_for_root(roots_by_slot[slot])
            if not got:
                continue
            fork, sidecars = got
            ns = self.types.by_fork[fork]
            if not hasattr(ns, "BlobSidecar"):
                continue
            digest = self.beacon_cfg.fork_digest(slot // spe)
            for sc in sidecars:
                if served >= MAX_REQUEST_BLOB_SIDECARS:
                    return
                yield (digest, ns.BlobSidecar.serialize(sc))
                served += 1

    async def on_blob_sidecars_by_root(self, peer, payload):
        """Serve sidecars by (block_root, index) identifier
        (handlers/blobSidecarsByRoot.ts)."""
        from ..network.wire_types import BlobSidecarsByRootRequest

        spe = preset().SLOTS_PER_EPOCH
        ids = BlobSidecarsByRootRequest.deserialize(payload)
        for ident in ids:
            got = self._blobs_for_root(bytes(ident.block_root))
            if not got:
                continue
            fork, sidecars = got
            ns = self.types.by_fork[fork]
            for sc in sidecars:
                if int(sc.index) != int(ident.index):
                    continue
                slot = int(sc.signed_block_header.message.slot)
                digest = self.beacon_cfg.fork_digest(slot // spe)
                yield (digest, ns.BlobSidecar.serialize(sc))

    def _lc_server(self):
        lc = getattr(self.chain, "light_client_server", None)
        if lc is None:
            raise rr.ReqRespError(
                rr.RESP_RESOURCE_UNAVAILABLE, "no light client server"
            )
        return lc

    def _lc_digest_for(self, obj, slot_attr) -> bytes:
        spe = preset().SLOTS_PER_EPOCH
        slot = int(slot_attr)
        return self.beacon_cfg.fork_digest(slot // spe)

    async def on_lc_bootstrap(self, peer, payload):
        """LightClientBootstrap by trusted block root
        (handlers, lightClientBootstrap.ts)."""
        from ..ssz import Root

        root = bytes(Root.deserialize(payload))
        lc = self._lc_server()
        boot = lc.get_bootstrap(root)
        if boot is None:
            raise rr.ReqRespError(
                rr.RESP_RESOURCE_UNAVAILABLE, "bootstrap unavailable"
            )
        slot = int(boot.header.beacon.slot)
        yield (
            self.beacon_cfg.fork_digest(slot // preset().SLOTS_PER_EPOCH),
            self.types.LightClientBootstrap.serialize(boot),
        )

    async def on_lc_finality_update(self, peer, payload):
        lc = self._lc_server()
        upd = lc.latest_finality_update
        if upd is None:
            raise rr.ReqRespError(
                rr.RESP_RESOURCE_UNAVAILABLE, "no finality update"
            )
        slot = int(upd.attested_header.beacon.slot)
        yield (
            self.beacon_cfg.fork_digest(slot // preset().SLOTS_PER_EPOCH),
            self.types.LightClientFinalityUpdate.serialize(upd),
        )

    async def on_lc_optimistic_update(self, peer, payload):
        lc = self._lc_server()
        upd = lc.latest_optimistic_update
        if upd is None:
            raise rr.ReqRespError(
                rr.RESP_RESOURCE_UNAVAILABLE, "no optimistic update"
            )
        slot = int(upd.attested_header.beacon.slot)
        yield (
            self.beacon_cfg.fork_digest(slot // preset().SLOTS_PER_EPOCH),
            self.types.LightClientOptimisticUpdate.serialize(upd),
        )

    async def on_lc_updates_by_range(self, peer, payload):
        """LightClientUpdatesByRange: one best update per sync-committee
        period (handlers, lightClientUpdatesByRange.ts)."""
        from ..network.wire_types import LightClientUpdatesByRangeRequest

        req = LightClientUpdatesByRangeRequest.deserialize(payload)
        lc = self._lc_server()
        start = int(req.start_period)
        count = min(int(req.count), 128)
        for period in range(start, start + count):
            upd = lc.best_update_by_period.get(period)
            if upd is None:
                continue
            slot = int(upd.attested_header.beacon.slot)
            yield (
                self.beacon_cfg.fork_digest(
                    slot // preset().SLOTS_PER_EPOCH
                ),
                self.types.LightClientUpdate.serialize(upd),
            )

    async def on_blocks_by_range(self, peer, payload):
        """Stream canonical blocks in [start, start+count) slot order
        (network/reqresp/handlers/beaconBlocksByRange.ts)."""
        req = BeaconBlocksByRangeRequest.deserialize(payload)
        start = int(req.start_slot)
        count = min(int(req.count), rr.MAX_REQUEST_BLOCKS)
        if count == 0:
            raise rr.ReqRespError(rr.RESP_INVALID_REQUEST, "count 0")
        chain = self.chain
        types = self.types
        spe = preset().SLOTS_PER_EPOCH
        served = 0
        # canonical chain walk: head back to start (hot part), plus the
        # finalized slot archive (db) for anything below
        by_slot = {}
        if chain.db is not None:
            for slot, (fork, block) in chain.db.block_archive.entries(
                start=start, end=start + count
            ):
                by_slot[slot] = (fork, block)
        node = chain.fork_choice.proto.get_node(chain.head_root)
        for n in chain.fork_choice.proto.iter_chain(chain.head_root):
            if start <= n.slot < start + count:
                got = self._block_by_root(n.block_root)
                if got is not None:
                    by_slot[n.slot] = got
        for slot in sorted(by_slot):
            fork, block = by_slot[slot]
            digest = self.beacon_cfg.fork_digest(slot // spe)
            yield (
                digest,
                self.types.by_fork[fork].SignedBeaconBlock.serialize(block),
            )
            served += 1

    async def on_blocks_by_root(self, peer, payload):
        """Serve blocks by root (handlers/beaconBlocksByRoot.ts)."""
        from ..network.wire_types import BeaconBlocksByRootRequest

        roots = BeaconBlocksByRootRequest.deserialize(payload)
        spe = preset().SLOTS_PER_EPOCH
        for root in roots[: rr.MAX_REQUEST_BLOCKS]:
            got = self._block_by_root(bytes(root))
            if got is None:
                continue
            fork, block = got
            digest = self.beacon_cfg.fork_digest(
                int(block.message.slot) // spe
            )
            yield (
                digest,
                self.types.by_fork[fork].SignedBeaconBlock.serialize(
                    block
                ),
            )

    def _block_by_root(self, root: bytes):
        blk = self.chain.get_block(root)
        if blk is not None:
            from ..statetransition.slot import fork_at_epoch

            fork = fork_at_epoch(
                self.chain.cfg,
                int(blk.message.slot) // preset().SLOTS_PER_EPOCH,
            )
            return (fork, blk)
        if self.chain.db is None:
            return None
        raw = self.chain.db.block.get_binary(root)
        if raw is None:
            return None
        return self.chain.db.block.decode_value(raw)


class RangeSync:
    """Client-side finalized-range sync loop (range/chain.ts:78,
    simplified to one SyncChain): pull batches from peers, import
    through the full verify pipeline, retry failed batches on another
    peer, stop at the target head."""

    def __init__(self, chain, beacon_cfg, types, node: rr.ReqResp,
                 clock=None, rng=None):
        from ..resilience.clock import SYSTEM_CLOCK

        self.chain = chain
        self.beacon_cfg = beacon_cfg
        self.types = types
        self.node = node
        self.clock = clock or SYSTEM_CLOCK
        self.rng = rng
        self.peers: list[str] = []
        self.peer_scores: dict[str, int] = {}
        self.banned_peers: set[str] = set()
        self.batches_processed = 0
        self.blocks_imported = 0

    def add_peer(self, peer_id: str) -> None:
        if peer_id not in self.peers and peer_id not in self.banned_peers:
            self.peers.append(peer_id)
            self.peer_scores.setdefault(peer_id, 0)

    def _downscore(self, peer: str, amount: int) -> None:
        """Repeated batch failures remove the peer from the rotation
        (reference: peer score -> goodbye/ban in peerManager)."""
        score = self.peer_scores.get(peer, 0) + amount
        self.peer_scores[peer] = score
        if score <= PEER_SCORE_MIN and peer in self.peers:
            self.peers.remove(peer)
            self.banned_peers.add(peer)

    def _upscore(self, peer: str) -> None:
        self.peer_scores[peer] = min(
            0, self.peer_scores.get(peer, 0) + PEER_SCORE_SUCCESS
        )

    async def status_handshake(self, peer: str):
        chunks = await self.node.request(
            peer,
            rr.PROTOCOL_STATUS,
            Status.serialize(
                SyncServer(self.chain, self.beacon_cfg, self.types)
                .local_status()
            ),
        )
        return Status.deserialize(chunks[0].payload)

    def _head_slot(self) -> int:
        n = self.chain.fork_choice.proto.get_node(self.chain.head_root)
        return n.slot if n else 0

    async def sync_to(self, target_slot: int) -> int:
        """Sync forward to target_slot; returns blocks imported."""
        spe = preset().SLOTS_PER_EPOCH
        batch_span = EPOCHS_PER_BATCH * spe
        imported_total = 0
        if not self.peers:
            raise RuntimeError("no peers")
        while self._head_slot() < target_slot:
            start = self._head_slot() + 1
            batch = Batch(start, min(batch_span, target_slot - start + 1))
            ok = await self._run_batch(batch)
            if not ok:
                raise RuntimeError(
                    f"batch at slot {batch.start_slot} failed after retries"
                )
            if not batch.blocks:
                break  # peer has nothing more for us
            imported_total += len(batch.blocks)
            self.batches_processed += 1
        return imported_total

    async def _backoff(self, batch: Batch) -> None:
        """Jittered exponential pause before re-attempting a failed
        batch — peers that just failed get breathing room instead of
        an immediate identical request (batch.ts retry semantics +
        jsonRpcHttpClient-style backoff)."""
        from ..resilience import backoff_delay

        attempt = batch.download_attempts + batch.processing_attempts - 1
        await self.clock.sleep(
            backoff_delay(
                max(0, attempt),
                BATCH_RETRY_BASE_DELAY,
                BATCH_RETRY_MAX_DELAY,
                rng=self.rng,
            )
        )

    async def _run_batch(self, batch: Batch) -> bool:
        while batch.download_attempts < MAX_BATCH_DOWNLOAD_ATTEMPTS:
            peer = self._pick_peer(batch)
            if peer is None:
                return False
            batch.status = BatchStatus.downloading
            batch.download_attempts += 1
            try:
                blocks = await self._download(peer, batch)
            except (rr.ReqRespError, asyncio.TimeoutError):
                batch.failed_peers.add(peer)
                self._downscore(peer, PEER_SCORE_BATCH_FAILURE)
                batch.status = BatchStatus.awaiting_download
                await self._backoff(batch)
                continue
            batch.blocks = blocks
            batch.status = BatchStatus.processing
            try:
                await self._process(batch)
            except Exception:
                batch.processing_attempts += 1
                batch.failed_peers.add(peer)
                self._downscore(peer, PEER_SCORE_BATCH_FAILURE)
                batch.status = BatchStatus.awaiting_download
                if batch.processing_attempts >= MAX_BATCH_PROCESSING_ATTEMPTS:
                    batch.status = BatchStatus.failed
                    return False
                await self._backoff(batch)
                continue
            self._upscore(peer)
            batch.status = BatchStatus.done
            return True
        batch.status = BatchStatus.failed
        return False

    def _pick_peer(self, batch: Batch) -> str | None:
        """Prefer peers that haven't failed this batch, then peers the
        reqresp layer hasn't been seeing failures from
        (peerBalancer.ts:10)."""
        fresh = [p for p in self.peers if p not in batch.failed_peers]
        pool = fresh or self.peers
        if not pool:
            return None
        stats = getattr(self.node, "peer_stats", None)
        if stats:
            # stable sort: healthy (no consecutive failures) first
            pool = sorted(
                pool,
                key=lambda p: stats[p].consecutive_failures
                if p in stats
                else 0,
            )
        return pool[batch.download_attempts % len(pool)]

    async def _download(self, peer: str, batch: Batch) -> list:
        req = BeaconBlocksByRangeRequest(
            start_slot=batch.start_slot, count=batch.count, step=1
        )
        chunks = await self.node.request(
            peer,
            rr.PROTOCOL_BLOCKS_BY_RANGE,
            BeaconBlocksByRangeRequest.serialize(req),
        )
        pairs = decode_block_chunks(self.beacon_cfg, self.types, chunks)
        batch.blobs_by_root = await self._download_blobs(
            peer, batch, pairs
        )
        return [block for _, block in pairs]

    async def _download_blobs(
        self, peer: str, batch: Batch, pairs
    ) -> dict[bytes, list]:
        """Fetch the span's blob sidecars when any block commits blobs
        (network/reqresp/beaconBlocksMaybeBlobsByRange.ts): blocks and
        sidecars ride the same peer + span, grouped by block root for
        the DA check at import."""
        from ..network.wire_types import BlobSidecarsByRangeRequest

        needs = False
        for fork, block in pairs:
            body = block.message.body
            comms = getattr(body, "blob_kzg_commitments", None)
            if comms is not None and len(comms) > 0:
                needs = True
                break
        if not needs:
            return {}
        req = BlobSidecarsByRangeRequest(
            start_slot=batch.start_slot, count=batch.count
        )
        chunks = await self.node.request(
            peer,
            rr.PROTOCOL_BLOB_SIDECARS_BY_RANGE,
            BlobSidecarsByRangeRequest.serialize(req),
        )
        out: dict[bytes, list] = {}
        for ch in chunks:
            fork = self.beacon_cfg.fork_name_from_digest(ch.context)
            ns = self.types.by_fork[fork]
            sc = ns.BlobSidecar.deserialize(ch.payload)
            hdr = sc.signed_block_header.message
            root = self.types.BeaconBlockHeader.hash_tree_root(hdr)
            out.setdefault(bytes(root), []).append(sc)
        return out

    async def _process(self, batch: Batch) -> None:
        """chain.processChainSegment analog: sequential import; each
        block's signature sets go through the batch verifier; deneb
        blocks carry their sidecars into the DA check."""
        for block in batch.blocks:
            root = None
            if batch.blobs_by_root:
                hdr_root = self.types.by_fork[
                    self._fork_of(block)
                ].BeaconBlock.hash_tree_root(block.message)
                root = bytes(hdr_root)
            await self.chain.process_block(
                block,
                is_timely=False,
                blob_sidecars=batch.blobs_by_root.get(root)
                if root is not None
                else None,
            )
            self.blocks_imported += 1

    def _fork_of(self, block):
        from ..statetransition.slot import fork_at_epoch

        return fork_at_epoch(
            self.chain.cfg,
            int(block.message.slot) // preset().SLOTS_PER_EPOCH,
        )

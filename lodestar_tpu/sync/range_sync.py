"""Range sync: epoch-batch download + batched-verify import.

Reference analog: sync/range/chain.ts:78 (SyncChain), batch.ts:62
(Batch state machine: AwaitingDownload -> Downloading -> AwaitingProcess
-> Processing -> AwaitingValidation, with retry + peer replacement on
failure), peerBalancer.ts:10. Downloads go through the reqresp
BeaconBlocksByRange protocol; imports run the chain's full pipeline, so
each batch's signatures hit the TPU verifier as bulk sets — the
reference's "~8,000 sigs per 64-block batch" shape (BASELINE.md).

`SyncServer` is the serving side: the reqresp handlers a node registers
so peers can sync from it (network/reqresp/handlers/*.ts).
"""

from __future__ import annotations

import asyncio
from enum import Enum

from ..network import reqresp as rr
from ..network.wire_types import (
    BeaconBlocksByRangeRequest,
    Status,
)
from ..params import preset

EPOCHS_PER_BATCH = 2  # range/batch.ts EPOCHS_PER_BATCH
MAX_BATCH_DOWNLOAD_ATTEMPTS = 5
MAX_BATCH_PROCESSING_ATTEMPTS = 3


def decode_block_chunks(beacon_cfg, types, chunks):
    """reqresp response chunks -> [(fork, SignedBeaconBlock)] using the
    per-chunk fork-digest context (shared by range/backfill/unknown
    sync)."""
    out = []
    for ch in chunks:
        fork = beacon_cfg.fork_name_from_digest(ch.context)
        out.append(
            (
                fork,
                types.by_fork[fork].SignedBeaconBlock.deserialize(
                    ch.payload
                ),
            )
        )
    return out


class BatchStatus(str, Enum):
    awaiting_download = "AwaitingDownload"
    downloading = "Downloading"
    awaiting_process = "AwaitingProcess"
    processing = "Processing"
    done = "Done"
    failed = "Failed"


class Batch:
    """One EPOCHS_PER_BATCH slot span (batch.ts:62)."""

    def __init__(self, start_slot: int, count: int):
        self.start_slot = start_slot
        self.count = count
        self.status = BatchStatus.awaiting_download
        self.blocks: list = []
        self.download_attempts = 0
        self.processing_attempts = 0
        self.failed_peers: set[str] = set()


class SyncServer:
    """Server-side reqresp handlers backed by a chain + db."""

    def __init__(self, chain, beacon_cfg, types):
        self.chain = chain
        self.beacon_cfg = beacon_cfg
        self.types = types

    def register(self, node: rr.ReqResp) -> None:
        node.register_handler(rr.PROTOCOL_STATUS, self.on_status)
        node.register_handler(rr.PROTOCOL_PING, self.on_ping)
        node.register_handler(
            rr.PROTOCOL_BLOCKS_BY_RANGE, self.on_blocks_by_range
        )
        node.register_handler(
            rr.PROTOCOL_BLOCKS_BY_ROOT, self.on_blocks_by_root
        )

    def local_status(self):
        chain = self.chain
        head = chain.fork_choice.proto.get_node(chain.head_root)
        fin = chain.finalized_checkpoint
        head_epoch = (head.slot if head else 0) // preset().SLOTS_PER_EPOCH
        st = Status(
            fork_digest=self.beacon_cfg.fork_digest(head_epoch),
            finalized_root=fin.root,
            finalized_epoch=fin.epoch,
            head_root=chain.head_root,
            head_slot=head.slot if head else 0,
        )
        return st

    async def on_status(self, peer, payload):
        yield (b"", Status.serialize(self.local_status()))

    async def on_ping(self, peer, payload):
        from ..ssz import uint64

        yield (b"", uint64.serialize(0))

    async def on_blocks_by_range(self, peer, payload):
        """Stream canonical blocks in [start, start+count) slot order
        (network/reqresp/handlers/beaconBlocksByRange.ts)."""
        req = BeaconBlocksByRangeRequest.deserialize(payload)
        start = int(req.start_slot)
        count = min(int(req.count), rr.MAX_REQUEST_BLOCKS)
        if count == 0:
            raise rr.ReqRespError(rr.RESP_INVALID_REQUEST, "count 0")
        chain = self.chain
        types = self.types
        spe = preset().SLOTS_PER_EPOCH
        served = 0
        # canonical chain walk: head back to start (hot part), plus the
        # finalized slot archive (db) for anything below
        by_slot = {}
        if chain.db is not None:
            for slot, (fork, block) in chain.db.block_archive.entries(
                start=start, end=start + count
            ):
                by_slot[slot] = (fork, block)
        node = chain.fork_choice.proto.get_node(chain.head_root)
        for n in chain.fork_choice.proto.iter_chain(chain.head_root):
            if start <= n.slot < start + count:
                got = self._block_by_root(n.block_root)
                if got is not None:
                    by_slot[n.slot] = got
        for slot in sorted(by_slot):
            fork, block = by_slot[slot]
            digest = self.beacon_cfg.fork_digest(slot // spe)
            yield (
                digest,
                self.types.by_fork[fork].SignedBeaconBlock.serialize(block),
            )
            served += 1

    async def on_blocks_by_root(self, peer, payload):
        """Serve blocks by root (handlers/beaconBlocksByRoot.ts)."""
        from ..network.wire_types import BeaconBlocksByRootRequest

        roots = BeaconBlocksByRootRequest.deserialize(payload)
        spe = preset().SLOTS_PER_EPOCH
        for root in roots[: rr.MAX_REQUEST_BLOCKS]:
            got = self._block_by_root(bytes(root))
            if got is None:
                continue
            fork, block = got
            digest = self.beacon_cfg.fork_digest(
                int(block.message.slot) // spe
            )
            yield (
                digest,
                self.types.by_fork[fork].SignedBeaconBlock.serialize(
                    block
                ),
            )

    def _block_by_root(self, root: bytes):
        blk = self.chain.get_block(root)
        if blk is not None:
            from ..statetransition.slot import fork_at_epoch

            fork = fork_at_epoch(
                self.chain.cfg,
                int(blk.message.slot) // preset().SLOTS_PER_EPOCH,
            )
            return (fork, blk)
        if self.chain.db is None:
            return None
        raw = self.chain.db.block.get_binary(root)
        if raw is None:
            return None
        return self.chain.db.block.decode_value(raw)


class RangeSync:
    """Client-side finalized-range sync loop (range/chain.ts:78,
    simplified to one SyncChain): pull batches from peers, import
    through the full verify pipeline, retry failed batches on another
    peer, stop at the target head."""

    def __init__(self, chain, beacon_cfg, types, node: rr.ReqResp):
        self.chain = chain
        self.beacon_cfg = beacon_cfg
        self.types = types
        self.node = node
        self.peers: list[str] = []
        self.batches_processed = 0
        self.blocks_imported = 0

    def add_peer(self, peer_id: str) -> None:
        if peer_id not in self.peers:
            self.peers.append(peer_id)

    async def status_handshake(self, peer: str):
        chunks = await self.node.request(
            peer,
            rr.PROTOCOL_STATUS,
            Status.serialize(
                SyncServer(self.chain, self.beacon_cfg, self.types)
                .local_status()
            ),
        )
        return Status.deserialize(chunks[0].payload)

    def _head_slot(self) -> int:
        n = self.chain.fork_choice.proto.get_node(self.chain.head_root)
        return n.slot if n else 0

    async def sync_to(self, target_slot: int) -> int:
        """Sync forward to target_slot; returns blocks imported."""
        spe = preset().SLOTS_PER_EPOCH
        batch_span = EPOCHS_PER_BATCH * spe
        imported_total = 0
        if not self.peers:
            raise RuntimeError("no peers")
        while self._head_slot() < target_slot:
            start = self._head_slot() + 1
            batch = Batch(start, min(batch_span, target_slot - start + 1))
            ok = await self._run_batch(batch)
            if not ok:
                raise RuntimeError(
                    f"batch at slot {batch.start_slot} failed after retries"
                )
            if not batch.blocks:
                break  # peer has nothing more for us
            imported_total += len(batch.blocks)
            self.batches_processed += 1
        return imported_total

    async def _run_batch(self, batch: Batch) -> bool:
        while batch.download_attempts < MAX_BATCH_DOWNLOAD_ATTEMPTS:
            peer = self._pick_peer(batch)
            if peer is None:
                return False
            batch.status = BatchStatus.downloading
            batch.download_attempts += 1
            try:
                blocks = await self._download(peer, batch)
            except (rr.ReqRespError, asyncio.TimeoutError):
                batch.failed_peers.add(peer)
                batch.status = BatchStatus.awaiting_download
                continue
            batch.blocks = blocks
            batch.status = BatchStatus.processing
            try:
                await self._process(batch)
            except Exception:
                batch.processing_attempts += 1
                batch.failed_peers.add(peer)
                batch.status = BatchStatus.awaiting_download
                if batch.processing_attempts >= MAX_BATCH_PROCESSING_ATTEMPTS:
                    batch.status = BatchStatus.failed
                    return False
                continue
            batch.status = BatchStatus.done
            return True
        batch.status = BatchStatus.failed
        return False

    def _pick_peer(self, batch: Batch) -> str | None:
        """Prefer peers that haven't failed this batch
        (peerBalancer.ts:10)."""
        fresh = [p for p in self.peers if p not in batch.failed_peers]
        pool = fresh or self.peers
        if not pool:
            return None
        return pool[batch.download_attempts % len(pool)]

    async def _download(self, peer: str, batch: Batch) -> list:
        req = BeaconBlocksByRangeRequest(
            start_slot=batch.start_slot, count=batch.count, step=1
        )
        chunks = await self.node.request(
            peer,
            rr.PROTOCOL_BLOCKS_BY_RANGE,
            BeaconBlocksByRangeRequest.serialize(req),
        )
        return [
            block
            for _, block in decode_block_chunks(
                self.beacon_cfg, self.types, chunks
            )
        ]

    async def _process(self, batch: Batch) -> None:
        """chain.processChainSegment analog: sequential import; each
        block's signature sets go through the batch verifier."""
        for block in batch.blocks:
            await self.chain.process_block(block, is_timely=False)
            self.blocks_imported += 1

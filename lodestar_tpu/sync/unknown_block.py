"""Unknown-block sync: repair gossip gaps by fetching ancestors.

Reference analog: UnknownBlockSync (sync/unknownBlock.ts:28) — when an
attestation or block references a root fork choice doesn't know, fetch
it (and unknown parents, recursively) over BeaconBlocksByRoot, then
import the recovered segment child-ward through the full pipeline.
"""

from __future__ import annotations

from ..network import reqresp as rr
from ..network.wire_types import BeaconBlocksByRootRequest

MAX_PARENT_CHAIN = 64  # unknownBlock.ts caps ancestor walks


class UnknownBlockSyncError(Exception):
    pass


class UnknownBlockSync:
    def __init__(self, chain, beacon_cfg, node: rr.ReqResp):
        self.chain = chain
        self.beacon_cfg = beacon_cfg
        self.node = node
        self.peers: list[str] = []
        self.fetched = 0
        self.imported = 0

    def add_peer(self, peer_id: str) -> None:
        if peer_id not in self.peers:
            self.peers.append(peer_id)

    async def on_unknown_block(self, root: bytes) -> int:
        """Resolve `root` into fork choice; returns blocks imported."""
        if self.chain.fork_choice.has_block(root):
            return 0
        if not self.peers:
            raise UnknownBlockSyncError("no peers to fetch from")
        segment = []  # child-most first
        want = root
        for _ in range(MAX_PARENT_CHAIN):
            block = await self._fetch_by_root(want)
            if block is None:
                raise UnknownBlockSyncError(
                    f"no peer served block {want.hex()[:16]}"
                )
            segment.append(block)
            parent = bytes(block.message.parent_root)
            if self.chain.fork_choice.has_block(parent):
                break
            want = parent
        else:
            raise UnknownBlockSyncError("parent chain too long")
        imported = 0
        for block in reversed(segment):
            await self.chain.process_block(block, is_timely=False)
            imported += 1
        self.imported += imported
        return imported

    async def _fetch_by_root(self, root: bytes):
        payload = BeaconBlocksByRootRequest.serialize([root])
        for peer in list(self.peers):
            try:
                chunks = await self.node.request(
                    peer, rr.PROTOCOL_BLOCKS_BY_ROOT, payload
                )
            except (rr.ReqRespError, TimeoutError):
                continue
            from .range_sync import decode_block_chunks

            for fork, block in decode_block_chunks(
                self.beacon_cfg, self.chain.types, chunks
            ):
                got_root = self.chain.types.by_fork[
                    fork
                ].BeaconBlock.hash_tree_root(block.message)
                if got_root != root:
                    # peer served the wrong block: don't let it steer
                    # which segment gets imported; try the next peer
                    continue
                self.fetched += 1
                return block
        return None

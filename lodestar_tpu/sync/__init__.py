"""Sync: range sync over reqresp.

Reference analog: packages/beacon-node/src/sync/ — `BeaconSync`
(sync.ts:19) switching head/range modes, `RangeSync`/`SyncChain`
(range/range.ts:77, range/chain.ts:78) with epoch-batch state machines
(range/batch.ts:62) and peer balancing (range/utils/peerBalancer.ts).
"""

from .backfill import BackfillError, BackfillSync
from .range_sync import Batch, BatchStatus, RangeSync, SyncServer
from .unknown_block import UnknownBlockSync, UnknownBlockSyncError

__all__ = [
    "BackfillError",
    "BackfillSync",
    "Batch",
    "BatchStatus",
    "RangeSync",
    "SyncServer",
    "UnknownBlockSync",
    "UnknownBlockSyncError",
]

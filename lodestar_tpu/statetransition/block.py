"""Block processing for every fork (phase0 → electra).

Reference analog: packages/state-transition/src/block/index.ts:31 and
its 22 operation processors (src/block/process*.ts), following
ethereum/consensus-specs beacon-chain.md per fork. Signature
verification is gated by ``verify_signatures`` — production block
import extracts signature sets instead (signature_sets.py) and batches
them through the TPU verifier, mirroring the reference's split between
stateTransition({verifySignatures:false}) and the BLS pool
(chain/blocks/verifyBlock.ts:38-100).
"""

from __future__ import annotations

from hashlib import sha256

import numpy as np

from ..ssz.cached import SszVec
from ..config.beacon_config import compute_domain, compute_signing_root_from_roots
from ..params import (
    BLS_WITHDRAWAL_PREFIX,
    COMPOUNDING_WITHDRAWAL_PREFIX,
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_BLS_TO_EXECUTION_CHANGE,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_VOLUNTARY_EXIT,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    FAR_FUTURE_EPOCH,
    GENESIS_SLOT,
    ForkSeq,
    preset,
)
from ..ssz import uint64 as ssz_uint64
from . import util
from .util import (
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    EpochShuffling,
    compute_epoch_at_slot,
    decrease_balance,
    get_block_root,
    get_block_root_at_slot,
    get_current_epoch,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    increase_balance,
    integer_squareroot,
)

FULL_EXIT_REQUEST_AMOUNT = 0
UNSET_DEPOSIT_REQUESTS_START_INDEX = 2**64 - 1
G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95


class BlockProcessError(AssertionError):
    pass


def _req(cond, msg: str) -> None:
    if not cond:
        raise BlockProcessError(msg)


# ---------------------------------------------------------------------------
# Domains / signing roots
# ---------------------------------------------------------------------------


def get_domain(cfg, state, domain_type: bytes, epoch: int | None = None) -> bytes:
    """Spec get_domain over the state's fork schedule."""
    if epoch is None:
        epoch = get_current_epoch(state)
    fork = state.fork
    version = (
        fork.previous_version if epoch < fork.epoch else fork.current_version
    )
    return compute_domain(domain_type, version, state.genesis_validators_root)


def compute_signing_root(ssz_type, value, domain: bytes) -> bytes:
    return compute_signing_root_from_roots(
        ssz_type.hash_tree_root(value), domain
    )


# ---------------------------------------------------------------------------
# Per-block context (memoized proposer / shufflings / base rewards)
# ---------------------------------------------------------------------------


class BlockCtx:
    """Caches recomputed-per-operation quantities for one block.
    Reference analog: EpochCache on CachedBeaconState
    (state-transition/src/cache/epochCache.ts:111)."""

    def __init__(self, cfg, state, types, fork_seq, verify_signatures):
        self.cfg = cfg
        self.state = state
        self.types = types
        self.fork_seq = fork_seq
        self.verify = verify_signatures
        self._shufflings: dict[int, EpochShuffling] = {}
        self._proposer: int | None = None
        self._total_active: int | None = None
        self._pubkey2index: dict[bytes, int] | None = None

    def pubkey2index(self) -> util.PubkeyIndexView:
        """Registry pubkey -> index map, shared process-wide and synced
        to this state's registry length (reference: pubkey-index-map /
        Index2PubkeyCache, pubkeyCache.ts:2)."""
        if self._pubkey2index is None:
            self._pubkey2index = util.PubkeyIndexView(self.state)
        return self._pubkey2index

    def shuffling(self, epoch: int) -> EpochShuffling:
        if epoch not in self._shufflings:
            self._shufflings[epoch] = util.get_shuffling(self.state, epoch)
        return self._shufflings[epoch]

    def proposer_index(self) -> int:
        if self._proposer is None:
            self._proposer = util.get_beacon_proposer_index(
                self.state, electra=self.fork_seq >= ForkSeq.electra
            )
        return self._proposer

    def total_active_balance(self) -> int:
        if self._total_active is None:
            self._total_active = get_total_active_balance(self.state)
        return self._total_active

    def base_reward(self, index: int) -> int:
        p = preset()
        increments = (
            self.state.validators[index].effective_balance
            // p.EFFECTIVE_BALANCE_INCREMENT
        )
        return increments * self.base_reward_per_increment()

    def base_reward_per_increment(self) -> int:
        p = preset()
        return (
            p.EFFECTIVE_BALANCE_INCREMENT
            * p.BASE_REWARD_FACTOR
            // integer_squareroot(self.total_active_balance())
        )


# ---------------------------------------------------------------------------
# Header / randao / eth1 data
# ---------------------------------------------------------------------------


def process_block_header(ctx, block) -> None:
    state, types = ctx.state, ctx.types
    _req(block.slot == state.slot, "block slot != state slot")
    _req(
        block.slot > state.latest_block_header.slot,
        "block not newer than latest header",
    )
    _req(
        block.proposer_index == ctx.proposer_index(),
        "wrong proposer index",
    )
    _req(
        bytes(block.parent_root)
        == types.BeaconBlockHeader.hash_tree_root(state.latest_block_header),
        "parent root mismatch",
    )
    header = types.BeaconBlockHeader.default()
    header.slot = block.slot
    header.proposer_index = block.proposer_index
    header.parent_root = block.parent_root
    header.state_root = b"\x00" * 32
    ns = types.by_fork[_fork_name(ctx.fork_seq)]
    # blinded bodies hash with the blinded type — the root is identical
    # to the full body's (header commits to the payload field-by-field)
    body_type = (
        ns.BeaconBlockBody
        if hasattr(block.body, "execution_payload")
        or not hasattr(ns, "BlindedBeaconBlockBody")
        else ns.BlindedBeaconBlockBody
    )
    header.body_root = body_type.hash_tree_root(block.body)
    state.latest_block_header = header
    _req(
        not state.validators[block.proposer_index].slashed,
        "proposer slashed",
    )


def _fork_name(fork_seq: int) -> str:
    from ..params import FORK_ORDER

    return FORK_ORDER[fork_seq]


def process_randao(ctx, body) -> None:
    state = ctx.state
    p = preset()
    epoch = get_current_epoch(state)
    if ctx.verify:
        from ..crypto.bls.signature import verify as bls_verify

        proposer = state.validators[ctx.proposer_index()]
        domain = get_domain(ctx.cfg, state, DOMAIN_RANDAO)
        root = compute_signing_root(ssz_uint64, epoch, domain)
        _req(
            bls_verify(bytes(proposer.pubkey), root, bytes(body.randao_reveal)),
            "invalid randao reveal",
        )
    mix = bytes(
        a ^ b
        for a, b in zip(
            get_randao_mix(state, epoch), sha256(bytes(body.randao_reveal)).digest()
        )
    )
    state.randao_mixes[epoch % p.EPOCHS_PER_HISTORICAL_VECTOR] = mix


def process_eth1_data(ctx, body) -> None:
    state, types = ctx.state, ctx.types
    p = preset()
    state.eth1_data_votes.append(body.eth1_data)
    target = types.Eth1Data.serialize(body.eth1_data)
    count = sum(
        1
        for v in state.eth1_data_votes
        if types.Eth1Data.serialize(v) == target
    )
    if count * 2 > p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH:
        state.eth1_data = body.eth1_data


# ---------------------------------------------------------------------------
# Attestations
# ---------------------------------------------------------------------------


def _checkpoint_eq(types, a, b) -> bool:
    return types.Checkpoint.serialize(a) == types.Checkpoint.serialize(b)


def _validate_attestation_data(ctx, data) -> None:
    state = ctx.state
    p = preset()
    prev, cur = get_previous_epoch(state), get_current_epoch(state)
    _req(data.target.epoch in (prev, cur), "target epoch not prev/cur")
    _req(
        data.target.epoch == compute_epoch_at_slot(data.slot),
        "target epoch != slot epoch",
    )
    _req(
        data.slot + p.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot,
        "attestation too fresh",
    )
    if ctx.fork_seq < ForkSeq.deneb:  # EIP-7045 removed the upper bound
        _req(
            state.slot <= data.slot + p.SLOTS_PER_EPOCH,
            "attestation too old",
        )


def get_attesting_indices(ctx, attestation) -> list[int]:
    """Validator indices attested to, per fork encoding (phase0 single
    committee bitlist; electra committee_bits + concatenated bits)."""
    data = attestation.data
    shuffling = ctx.shuffling(data.target.epoch)
    if ctx.fork_seq >= ForkSeq.electra:
        out = []
        offset = 0
        bits = list(attestation.aggregation_bits)
        for ci, has in enumerate(attestation.committee_bits):
            if not has:
                continue
            committee = shuffling.committee(data.slot, ci)
            members = [
                int(v)
                for i, v in enumerate(committee)
                if bits[offset + i]
            ]
            out.extend(members)
            offset += len(committee)
        return out
    committee = shuffling.committee(data.slot, data.index)
    bits = list(attestation.aggregation_bits)
    return [int(v) for i, v in enumerate(committee) if bits[i]]


def is_valid_indexed_attestation(ctx, indexed) -> bool:
    indices = [int(i) for i in indexed.attesting_indices]
    if len(indices) == 0 or indices != sorted(set(indices)):
        return False
    if indices[-1] >= len(ctx.state.validators):
        return False  # unknown validator: invalid, not a crash
    if not ctx.verify:
        return True
    from ..crypto.bls.signature import fast_aggregate_verify

    state = ctx.state
    pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
    domain = get_domain(
        ctx.cfg, state, DOMAIN_BEACON_ATTESTER, indexed.data.target.epoch
    )
    root = compute_signing_root(
        ctx.types.AttestationData, indexed.data, domain
    )
    return fast_aggregate_verify(pubkeys, root, bytes(indexed.signature))


def _indexed_from_attestation(ctx, attestation):
    t = (
        ctx.types.electra.IndexedAttestation
        if ctx.fork_seq >= ForkSeq.electra
        else ctx.types.IndexedAttestation
    )
    out = t.default()
    out.attesting_indices = sorted(get_attesting_indices(ctx, attestation))
    out.data = attestation.data
    out.signature = attestation.signature
    return out


def get_attestation_participation_flag_indices(
    ctx, data, inclusion_delay: int
) -> list[int]:
    state = ctx.state
    p = preset()
    justified = (
        state.current_justified_checkpoint
        if data.target.epoch == get_current_epoch(state)
        else state.previous_justified_checkpoint
    )
    is_matching_source = _checkpoint_eq(ctx.types, data.source, justified)
    _req(is_matching_source, "attestation source != justified checkpoint")
    is_matching_target = is_matching_source and bytes(
        data.target.root
    ) == get_block_root(state, data.target.epoch)
    is_matching_head = False
    if is_matching_target:
        try:
            is_matching_head = bytes(
                data.beacon_block_root
            ) == get_block_root_at_slot(state, data.slot)
        except ValueError:
            is_matching_head = False
    flags = []
    if is_matching_source and inclusion_delay <= integer_squareroot(
        p.SLOTS_PER_EPOCH
    ):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if ctx.fork_seq >= ForkSeq.deneb:
        if is_matching_target:  # EIP-7045: no delay bound
            flags.append(TIMELY_TARGET_FLAG_INDEX)
    elif is_matching_target and inclusion_delay <= p.SLOTS_PER_EPOCH:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if (
        is_matching_head
        and inclusion_delay == p.MIN_ATTESTATION_INCLUSION_DELAY
    ):
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def process_attestation(ctx, attestation) -> None:
    state = ctx.state
    p = preset()
    data = attestation.data
    _validate_attestation_data(ctx, data)

    if ctx.fork_seq >= ForkSeq.electra:
        _req(data.index == 0, "electra attestation data.index != 0")
        shuffling = ctx.shuffling(data.target.epoch)
        bits = list(attestation.aggregation_bits)
        selected = [
            ci
            for ci, has in enumerate(attestation.committee_bits)
            if has
        ]
        _req(len(selected) > 0, "no committee bits set")
        committees = []
        total = 0
        for ci in selected:
            _req(
                ci < shuffling.committees_per_slot,
                "committee index out of range",
            )
            committee = shuffling.committee(data.slot, ci)
            committees.append(committee)
            total += len(committee)
        _req(len(bits) == total, "aggregation bits length mismatch")
        offset = 0
        for committee in committees:
            members = [i for i in range(len(committee)) if bits[offset + i]]
            _req(len(members) > 0, "empty committee participation")
            offset += len(committee)
    else:
        shuffling = ctx.shuffling(data.target.epoch)
        _req(
            data.index < shuffling.committees_per_slot,
            "committee index out of range",
        )
        committee = shuffling.committee(data.slot, data.index)
        _req(
            len(attestation.aggregation_bits) == len(committee),
            "aggregation bits length mismatch",
        )

    indexed = _indexed_from_attestation(ctx, attestation)
    _req(
        is_valid_indexed_attestation(ctx, indexed),
        "invalid indexed attestation",
    )

    if ctx.fork_seq >= ForkSeq.altair:
        inclusion_delay = state.slot - data.slot
        flag_indices = get_attestation_participation_flag_indices(
            ctx, data, inclusion_delay
        )
        epoch_participation = (
            state.current_epoch_participation
            if data.target.epoch == get_current_epoch(state)
            else state.previous_epoch_participation
        )
        proposer_reward_numerator = 0
        for index in indexed.attesting_indices:
            for flag_index, weight in enumerate(
                util.PARTICIPATION_FLAG_WEIGHTS
            ):
                if flag_index in flag_indices and not util.has_flag(
                    epoch_participation[index], flag_index
                ):
                    epoch_participation[index] = util.add_flag(
                        epoch_participation[index], flag_index
                    )
                    proposer_reward_numerator += (
                        ctx.base_reward(index) * weight
                    )
        denominator = (
            (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
            * WEIGHT_DENOMINATOR
            // PROPOSER_WEIGHT
        )
        increase_balance(
            state, ctx.proposer_index(), proposer_reward_numerator // denominator
        )
    else:
        pending = ctx.types.PendingAttestation.default()
        pending.aggregation_bits = list(attestation.aggregation_bits)
        pending.data = data
        pending.inclusion_delay = state.slot - data.slot
        pending.proposer_index = ctx.proposer_index()
        if data.target.epoch == get_current_epoch(state):
            _req(
                _checkpoint_eq(
                    ctx.types, data.source, state.current_justified_checkpoint
                ),
                "source != current justified",
            )
            state.current_epoch_attestations.append(pending)
        else:
            _req(
                _checkpoint_eq(
                    ctx.types, data.source, state.previous_justified_checkpoint
                ),
                "source != previous justified",
            )
            state.previous_epoch_attestations.append(pending)


# ---------------------------------------------------------------------------
# Slashings
# ---------------------------------------------------------------------------


def is_slashable_attestation_data(types, data_1, data_2) -> bool:
    double = (
        types.AttestationData.serialize(data_1)
        != types.AttestationData.serialize(data_2)
        and data_1.target.epoch == data_2.target.epoch
    )
    surround = (
        data_1.source.epoch < data_2.source.epoch
        and data_2.target.epoch < data_1.target.epoch
    )
    return double or surround


def process_proposer_slashing(ctx, proposer_slashing) -> None:
    state, types = ctx.state, ctx.types
    h1 = proposer_slashing.signed_header_1.message
    h2 = proposer_slashing.signed_header_2.message
    _req(h1.slot == h2.slot, "slots differ")
    _req(h1.proposer_index == h2.proposer_index, "proposer differs")
    _req(
        types.BeaconBlockHeader.serialize(h1)
        != types.BeaconBlockHeader.serialize(h2),
        "identical headers",
    )
    proposer = state.validators[h1.proposer_index]
    _req(
        util.is_slashable_validator(proposer, get_current_epoch(state)),
        "proposer not slashable",
    )
    if ctx.verify:
        from ..crypto.bls.signature import verify as bls_verify

        for signed in (
            proposer_slashing.signed_header_1,
            proposer_slashing.signed_header_2,
        ):
            domain = get_domain(
                ctx.cfg,
                state,
                DOMAIN_BEACON_PROPOSER,
                compute_epoch_at_slot(signed.message.slot),
            )
            root = compute_signing_root(
                types.BeaconBlockHeader, signed.message, domain
            )
            _req(
                bls_verify(
                    bytes(proposer.pubkey), root, bytes(signed.signature)
                ),
                "bad proposer slashing signature",
            )
    util.slash_validator(
        ctx.cfg, state, int(h1.proposer_index), ctx.fork_seq
    )


def process_attester_slashing(ctx, attester_slashing) -> None:
    state = ctx.state
    att1 = attester_slashing.attestation_1
    att2 = attester_slashing.attestation_2
    _req(
        is_slashable_attestation_data(ctx.types, att1.data, att2.data),
        "attestation data not slashable",
    )
    _req(is_valid_indexed_attestation(ctx, att1), "invalid attestation 1")
    _req(is_valid_indexed_attestation(ctx, att2), "invalid attestation 2")
    slashed_any = False
    common = set(int(i) for i in att1.attesting_indices) & set(
        int(i) for i in att2.attesting_indices
    )
    for index in sorted(common):
        if util.is_slashable_validator(
            state.validators[index], get_current_epoch(state)
        ):
            util.slash_validator(ctx.cfg, state, index, ctx.fork_seq)
            slashed_any = True
    _req(slashed_any, "no validator slashed")


# ---------------------------------------------------------------------------
# Deposits
# ---------------------------------------------------------------------------


def is_valid_merkle_branch(
    leaf: bytes, branch, depth: int, index: int, root: bytes
) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = sha256(bytes(branch[i]) + value).digest()
        else:
            value = sha256(value + bytes(branch[i])).digest()
    return value == bytes(root)


def is_valid_deposit_signature(
    cfg, pubkey, withdrawal_credentials, amount, signature, types
) -> bool:
    from ..crypto.bls.signature import verify as bls_verify

    msg = types.DepositMessage.default()
    msg.pubkey = pubkey
    msg.withdrawal_credentials = withdrawal_credentials
    msg.amount = amount
    domain = compute_domain(DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION, b"\x00" * 32)
    root = compute_signing_root(types.DepositMessage, msg, domain)
    try:
        return bls_verify(bytes(pubkey), root, bytes(signature))
    except Exception:
        return False


def has_eth1_withdrawal_credential(wc: bytes) -> bool:
    return bytes(wc[:1]) == ETH1_ADDRESS_WITHDRAWAL_PREFIX


def has_compounding_withdrawal_credential(wc: bytes) -> bool:
    return bytes(wc[:1]) == COMPOUNDING_WITHDRAWAL_PREFIX


def has_execution_withdrawal_credential(wc: bytes) -> bool:
    return has_eth1_withdrawal_credential(wc) or has_compounding_withdrawal_credential(wc)


def get_max_effective_balance(wc: bytes) -> int:
    p = preset()
    if has_compounding_withdrawal_credential(wc):
        return p.MAX_EFFECTIVE_BALANCE_ELECTRA
    return p.MIN_ACTIVATION_BALANCE


def add_validator_to_registry(
    cfg, state, pubkey, withdrawal_credentials, amount, types, fork_seq
) -> None:
    p = preset()
    v = types.Validator.default()
    v.pubkey = bytes(pubkey)
    v.withdrawal_credentials = bytes(withdrawal_credentials)
    v.activation_eligibility_epoch = FAR_FUTURE_EPOCH
    v.activation_epoch = FAR_FUTURE_EPOCH
    v.exit_epoch = FAR_FUTURE_EPOCH
    v.withdrawable_epoch = FAR_FUTURE_EPOCH
    v.slashed = False
    if fork_seq >= ForkSeq.electra:
        max_eb = get_max_effective_balance(bytes(withdrawal_credentials))
    else:
        max_eb = p.MAX_EFFECTIVE_BALANCE
    v.effective_balance = min(
        amount - amount % p.EFFECTIVE_BALANCE_INCREMENT, max_eb
    )
    state.validators.append(v)
    state.balances.append(int(amount))
    if hasattr(state, "previous_epoch_participation"):
        state.previous_epoch_participation.append(0)
        state.current_epoch_participation.append(0)
        state.inactivity_scores.append(0)


def apply_deposit(
    ctx, pubkey, withdrawal_credentials, amount, signature
) -> None:
    state, types, cfg = ctx.state, ctx.types, ctx.cfg
    index = ctx.pubkey2index().get(bytes(pubkey))
    if ctx.fork_seq >= ForkSeq.electra:
        if index is None:
            if is_valid_deposit_signature(
                cfg, pubkey, withdrawal_credentials, amount, signature, types
            ):
                add_validator_to_registry(
                    cfg,
                    state,
                    pubkey,
                    withdrawal_credentials,
                    0,
                    types,
                    ctx.fork_seq,
                )
            else:
                return
        pd = types.PendingDeposit.default()
        pd.pubkey = bytes(pubkey)
        pd.withdrawal_credentials = bytes(withdrawal_credentials)
        pd.amount = amount
        pd.signature = bytes(signature)
        pd.slot = GENESIS_SLOT
        state.pending_deposits.append(pd)
        return
    if index is None:
        if is_valid_deposit_signature(
            cfg, pubkey, withdrawal_credentials, amount, signature, types
        ):
            add_validator_to_registry(
                cfg,
                state,
                pubkey,
                withdrawal_credentials,
                amount,
                types,
                ctx.fork_seq,
            )
    else:
        increase_balance(state, index, amount)


def process_deposit(ctx, deposit) -> None:
    from ..params import DEPOSIT_CONTRACT_TREE_DEPTH

    state, types = ctx.state, ctx.types
    leaf = types.DepositData.hash_tree_root(deposit.data)
    _req(
        is_valid_merkle_branch(
            leaf,
            deposit.proof,
            DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            state.eth1_deposit_index,
            state.eth1_data.deposit_root,
        ),
        "invalid deposit proof",
    )
    state.eth1_deposit_index += 1
    apply_deposit(
        ctx,
        deposit.data.pubkey,
        deposit.data.withdrawal_credentials,
        deposit.data.amount,
        deposit.data.signature,
    )


# ---------------------------------------------------------------------------
# Voluntary exits
# ---------------------------------------------------------------------------


def get_pending_balance_to_withdraw(state, index: int) -> int:
    return sum(
        w.amount
        for w in state.pending_partial_withdrawals
        if w.validator_index == index
    )


def process_voluntary_exit(ctx, signed_exit) -> None:
    state, cfg = ctx.state, ctx.cfg
    exit_msg = signed_exit.message
    index = int(exit_msg.validator_index)
    validator = state.validators[index]
    cur = get_current_epoch(state)
    _req(util.is_active_validator(validator, cur), "not active")
    _req(validator.exit_epoch == FAR_FUTURE_EPOCH, "already exiting")
    _req(cur >= exit_msg.epoch, "exit epoch in future")
    _req(
        cur >= validator.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD,
        "too young to exit",
    )
    if ctx.fork_seq >= ForkSeq.electra:
        _req(
            get_pending_balance_to_withdraw(state, index) == 0,
            "pending partial withdrawals exist",
        )
    if ctx.verify:
        from ..crypto.bls.signature import verify as bls_verify

        if ctx.fork_seq >= ForkSeq.deneb:
            # EIP-7044: locked to capella fork domain
            domain = compute_domain(
                DOMAIN_VOLUNTARY_EXIT,
                cfg.CAPELLA_FORK_VERSION,
                state.genesis_validators_root,
            )
        else:
            domain = get_domain(
                cfg, state, DOMAIN_VOLUNTARY_EXIT, exit_msg.epoch
            )
        root = compute_signing_root(ctx.types.VoluntaryExit, exit_msg, domain)
        _req(
            bls_verify(
                bytes(validator.pubkey), root, bytes(signed_exit.signature)
            ),
            "bad voluntary exit signature",
        )
    if ctx.fork_seq >= ForkSeq.electra:
        util.initiate_validator_exit_electra(cfg, state, index)
    else:
        util.initiate_validator_exit(cfg, state, index)


# ---------------------------------------------------------------------------
# Sync aggregate (altair+)
# ---------------------------------------------------------------------------


def process_sync_aggregate(ctx, sync_aggregate) -> None:
    state, cfg = ctx.state, ctx.cfg
    p = preset()
    bits = list(sync_aggregate.sync_committee_bits)
    previous_slot = max(state.slot, 1) - 1
    if ctx.verify:
        from ..crypto.bls.signature import eth_fast_aggregate_verify

        committee_pubkeys = [
            bytes(pk) for pk in state.current_sync_committee.pubkeys
        ]
        participants = [pk for pk, b in zip(committee_pubkeys, bits) if b]
        domain = get_domain(
            cfg,
            state,
            DOMAIN_SYNC_COMMITTEE,
            compute_epoch_at_slot(previous_slot),
        )
        root = compute_signing_root_from_roots(
            get_block_root_at_slot(state, previous_slot), domain
        )
        _req(
            eth_fast_aggregate_verify(
                participants, root, bytes(sync_aggregate.sync_committee_signature)
            ),
            "bad sync aggregate signature",
        )
    total_active_increments = (
        ctx.total_active_balance() // p.EFFECTIVE_BALANCE_INCREMENT
    )
    total_base_rewards = (
        ctx.base_reward_per_increment() * total_active_increments
    )
    max_participant_rewards = (
        total_base_rewards
        * SYNC_REWARD_WEIGHT
        // WEIGHT_DENOMINATOR
        // p.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // p.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward
        * PROPOSER_WEIGHT
        // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )
    pubkey2index = ctx.pubkey2index()
    proposer = ctx.proposer_index()
    for pk, bit in zip(state.current_sync_committee.pubkeys, bits):
        participant = pubkey2index[bytes(pk)]
        if bit:
            increase_balance(state, participant, participant_reward)
            increase_balance(state, proposer, proposer_reward)
        else:
            decrease_balance(state, participant, participant_reward)


# ---------------------------------------------------------------------------
# Execution payload + withdrawals (bellatrix+/capella+)
# ---------------------------------------------------------------------------


def is_merge_transition_complete(ctx) -> bool:
    header_t = ctx.types.by_fork[
        _fork_name(ctx.fork_seq)
    ].ExecutionPayloadHeader
    default = header_t.serialize(header_t.default())
    return (
        header_t.serialize(ctx.state.latest_execution_payload_header)
        != default
    )


def compute_timestamp_at_slot(cfg, state, slot: int) -> int:
    return state.genesis_time + slot * cfg.SECONDS_PER_SLOT


def process_execution_payload(ctx, body, execution_engine=None) -> None:
    """Handles full AND blinded bodies: a blinded body carries the
    ExecutionPayloadHeader whose parent/randao/timestamp fields are
    checked identically and which becomes latest_execution_payload_
    header directly (reference: processExecutionPayload over
    FullOrBlindedExecutionPayload)."""
    state, cfg, types = ctx.state, ctx.cfg, ctx.types
    p = preset()
    blinded = not hasattr(body, "execution_payload")
    payload = (
        body.execution_payload_header if blinded
        else body.execution_payload
    )
    if ctx.fork_seq >= ForkSeq.capella or is_merge_transition_complete(ctx):
        _req(
            bytes(payload.parent_hash)
            == bytes(state.latest_execution_payload_header.block_hash),
            "payload parent hash mismatch",
        )
    _req(
        bytes(payload.prev_randao)
        == get_randao_mix(state, get_current_epoch(state)),
        "payload prev_randao mismatch",
    )
    _req(
        payload.timestamp == compute_timestamp_at_slot(cfg, state, state.slot),
        "payload timestamp mismatch",
    )
    if ctx.fork_seq >= ForkSeq.deneb:
        max_blobs = (
            cfg.MAX_BLOBS_PER_BLOCK_ELECTRA
            if ctx.fork_seq >= ForkSeq.electra
            else p.MAX_BLOBS_PER_BLOCK
        )
        _req(
            len(body.blob_kzg_commitments) <= max_blobs,
            "too many blobs",
        )
    if execution_engine is not None and not blinded:
        _req(
            execution_engine.notify_new_payload(payload),
            "execution engine rejected payload",
        )
    ns = types.by_fork[_fork_name(ctx.fork_seq)]
    if blinded:
        header = ns.ExecutionPayloadHeader.default()
        for name, _ in ns.ExecutionPayloadHeader.fields:
            setattr(header, name, getattr(payload, name))
    else:
        header = payload_to_header(ns, payload)
    state.latest_execution_payload_header = header


def payload_to_header(ns, payload):
    """ExecutionPayload -> ExecutionPayloadHeader (list fields become
    their hash-tree-roots). Shared by the state transition and the
    builder/relay machinery — the commitment rules must never drift."""
    header = ns.ExecutionPayloadHeader.default()
    for name, _ in ns.ExecutionPayloadHeader.fields:
        if name == "transactions_root":
            tx_t = ns.ExecutionPayload.field_types["transactions"]
            header.transactions_root = tx_t.hash_tree_root(
                payload.transactions
            )
        elif name == "withdrawals_root":
            w_t = ns.ExecutionPayload.field_types["withdrawals"]
            header.withdrawals_root = w_t.hash_tree_root(
                payload.withdrawals
            )
        else:
            setattr(header, name, getattr(payload, name))
    return header


def is_fully_withdrawable_validator(
    fork_seq, v, balance: int, epoch: int
) -> bool:
    wc = bytes(v.withdrawal_credentials)
    if fork_seq >= ForkSeq.electra:
        has_cred = has_execution_withdrawal_credential(wc)
    else:
        has_cred = has_eth1_withdrawal_credential(wc)
    return has_cred and v.withdrawable_epoch <= epoch and balance > 0


def is_partially_withdrawable_validator(fork_seq, v, balance: int) -> bool:
    p = preset()
    wc = bytes(v.withdrawal_credentials)
    if fork_seq >= ForkSeq.electra:
        if not has_execution_withdrawal_credential(wc):
            return False
        max_eb = get_max_effective_balance(wc)
        return v.effective_balance == max_eb and balance > max_eb
    return (
        has_eth1_withdrawal_credential(wc)
        and v.effective_balance == p.MAX_EFFECTIVE_BALANCE
        and balance > p.MAX_EFFECTIVE_BALANCE
    )


def get_expected_withdrawals(ctx):
    """Returns (withdrawals, partial_withdrawals_count)."""
    state, types = ctx.state, ctx.types
    p = preset()
    epoch = get_current_epoch(state)
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    withdrawals = []
    partial_count = 0

    if ctx.fork_seq >= ForkSeq.electra:
        for w in state.pending_partial_withdrawals:
            if (
                w.withdrawable_epoch > epoch
                or len(withdrawals)
                == p.MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP
            ):
                break
            v = state.validators[w.validator_index]
            has_sufficient = (
                v.effective_balance >= p.MIN_ACTIVATION_BALANCE
            )
            has_excess = (
                state.balances[w.validator_index] > p.MIN_ACTIVATION_BALANCE
            )
            if (
                v.exit_epoch == FAR_FUTURE_EPOCH
                and has_sufficient
                and has_excess
            ):
                amount = min(
                    state.balances[w.validator_index]
                    - p.MIN_ACTIVATION_BALANCE,
                    w.amount,
                )
                wd = types.Withdrawal.default()
                wd.index = withdrawal_index
                wd.validator_index = w.validator_index
                wd.address = bytes(v.withdrawal_credentials)[12:]
                wd.amount = amount
                withdrawals.append(wd)
                withdrawal_index += 1
            partial_count += 1

    bound = min(len(state.validators), p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    for _ in range(bound):
        v = state.validators[validator_index]
        balance = state.balances[validator_index] - sum(
            w.amount
            for w in withdrawals
            if w.validator_index == validator_index
        )
        if is_fully_withdrawable_validator(ctx.fork_seq, v, balance, epoch):
            wd = types.Withdrawal.default()
            wd.index = withdrawal_index
            wd.validator_index = validator_index
            wd.address = bytes(v.withdrawal_credentials)[12:]
            wd.amount = balance
            withdrawals.append(wd)
            withdrawal_index += 1
        elif is_partially_withdrawable_validator(ctx.fork_seq, v, balance):
            if ctx.fork_seq >= ForkSeq.electra:
                max_eb = get_max_effective_balance(
                    bytes(v.withdrawal_credentials)
                )
            else:
                max_eb = p.MAX_EFFECTIVE_BALANCE
            wd = types.Withdrawal.default()
            wd.index = withdrawal_index
            wd.validator_index = validator_index
            wd.address = bytes(v.withdrawal_credentials)[12:]
            wd.amount = balance - max_eb
            withdrawals.append(wd)
            withdrawal_index += 1
        if len(withdrawals) == p.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = (validator_index + 1) % len(state.validators)
    return withdrawals, partial_count


def process_withdrawals(ctx, payload) -> None:
    """`payload` is an ExecutionPayload OR (blinded blocks) an
    ExecutionPayloadHeader — the header commits to the withdrawals via
    withdrawals_root, checked against the expected list's root
    (reference: processWithdrawals over BlindedBeaconBlock bodies)."""
    state, types = ctx.state, ctx.types
    p = preset()
    expected, partial_count = get_expected_withdrawals(ctx)
    if hasattr(payload, "withdrawals"):
        got = list(payload.withdrawals)
        _req(len(got) == len(expected), "withdrawals count mismatch")
        for a, b in zip(got, expected):
            _req(
                types.Withdrawal.serialize(a)
                == types.Withdrawal.serialize(b),
                "withdrawal mismatch",
            )
    else:
        ns = types.by_fork[_fork_name(ctx.fork_seq)]
        w_t = ns.ExecutionPayload.field_types["withdrawals"]
        _req(
            bytes(payload.withdrawals_root)
            == w_t.hash_tree_root(expected),
            "withdrawals root mismatch",
        )
    for w in expected:
        decrease_balance(state, int(w.validator_index), int(w.amount))
    if ctx.fork_seq >= ForkSeq.electra and partial_count:
        state.pending_partial_withdrawals = SszVec(
            state.pending_partial_withdrawals[partial_count:]
        )
    if expected:
        state.next_withdrawal_index = expected[-1].index + 1
    if len(expected) == p.MAX_WITHDRAWALS_PER_PAYLOAD:
        state.next_withdrawal_validator_index = (
            expected[-1].validator_index + 1
        ) % len(state.validators)
    else:
        state.next_withdrawal_validator_index = (
            state.next_withdrawal_validator_index
            + p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP
        ) % len(state.validators)


def process_bls_to_execution_change(ctx, signed_change) -> None:
    state, cfg, types = ctx.state, ctx.cfg, ctx.types
    change = signed_change.message
    v = state.validators[change.validator_index]
    wc = bytes(v.withdrawal_credentials)
    _req(wc[:1] == BLS_WITHDRAWAL_PREFIX, "not a BLS credential")
    _req(
        wc[1:] == sha256(bytes(change.from_bls_pubkey)).digest()[1:],
        "from_bls_pubkey mismatch",
    )
    if ctx.verify:
        from ..crypto.bls.signature import verify as bls_verify

        domain = compute_domain(
            DOMAIN_BLS_TO_EXECUTION_CHANGE,
            cfg.GENESIS_FORK_VERSION,
            state.genesis_validators_root,
        )
        root = compute_signing_root(
            types.BLSToExecutionChange, change, domain
        )
        _req(
            bls_verify(
                bytes(change.from_bls_pubkey),
                root,
                bytes(signed_change.signature),
            ),
            "bad bls-to-execution-change signature",
        )
    v = util.mut(state.validators, int(change.validator_index))
    v.withdrawal_credentials = (
        ETH1_ADDRESS_WITHDRAWAL_PREFIX
        + b"\x00" * 11
        + bytes(change.to_execution_address)
    )


# ---------------------------------------------------------------------------
# Electra execution requests
# ---------------------------------------------------------------------------


def process_deposit_request(ctx, request) -> None:
    state, types = ctx.state, ctx.types
    if state.deposit_requests_start_index == UNSET_DEPOSIT_REQUESTS_START_INDEX:
        state.deposit_requests_start_index = request.index
    pd = types.PendingDeposit.default()
    pd.pubkey = bytes(request.pubkey)
    pd.withdrawal_credentials = bytes(request.withdrawal_credentials)
    pd.amount = request.amount
    pd.signature = bytes(request.signature)
    pd.slot = state.slot
    state.pending_deposits.append(pd)


def process_withdrawal_request(ctx, request) -> None:
    state, cfg, types = ctx.state, ctx.cfg, ctx.types
    p = preset()
    amount = request.amount
    is_full_exit = amount == FULL_EXIT_REQUEST_AMOUNT
    if (
        len(state.pending_partial_withdrawals)
        == p.PENDING_PARTIAL_WITHDRAWALS_LIMIT
        and not is_full_exit
    ):
        return
    index = ctx.pubkey2index().get(bytes(request.validator_pubkey))
    if index is None:
        return
    v = state.validators[index]
    wc = bytes(v.withdrawal_credentials)
    if not (
        has_execution_withdrawal_credential(wc)
        and wc[12:] == bytes(request.source_address)
    ):
        return
    cur = get_current_epoch(state)
    if not util.is_active_validator(v, cur):
        return
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    if cur < v.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD:
        return
    pending = get_pending_balance_to_withdraw(state, index)
    if is_full_exit:
        if pending == 0:
            util.initiate_validator_exit_electra(cfg, state, index)
        return
    has_sufficient = v.effective_balance >= p.MIN_ACTIVATION_BALANCE
    has_excess = state.balances[index] > p.MIN_ACTIVATION_BALANCE + pending
    if (
        has_compounding_withdrawal_credential(wc)
        and has_sufficient
        and has_excess
    ):
        to_withdraw = min(
            state.balances[index] - p.MIN_ACTIVATION_BALANCE - pending,
            amount,
        )
        exit_queue_epoch = util.compute_exit_epoch_and_update_churn(
            cfg, state, to_withdraw
        )
        ppw = types.PendingPartialWithdrawal.default()
        ppw.validator_index = index
        ppw.amount = to_withdraw
        ppw.withdrawable_epoch = (
            exit_queue_epoch + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        )
        state.pending_partial_withdrawals.append(ppw)


def compute_consolidation_epoch_and_update_churn(
    cfg, state, consolidation_balance: int
) -> int:
    from .util import (
        compute_activation_exit_epoch,
        get_consolidation_churn_limit,
    )

    earliest = max(
        state.earliest_consolidation_epoch,
        compute_activation_exit_epoch(get_current_epoch(state)),
    )
    per_epoch = get_consolidation_churn_limit(cfg, state)
    if state.earliest_consolidation_epoch < earliest:
        balance_to_consume = per_epoch
    else:
        balance_to_consume = state.consolidation_balance_to_consume
    if consolidation_balance > balance_to_consume:
        to_process = consolidation_balance - balance_to_consume
        additional_epochs = (to_process - 1) // per_epoch + 1
        earliest += additional_epochs
        balance_to_consume += additional_epochs * per_epoch
    state.consolidation_balance_to_consume = (
        balance_to_consume - consolidation_balance
    )
    state.earliest_consolidation_epoch = earliest
    return earliest


def switch_to_compounding_validator(ctx, index: int) -> None:
    state, types = ctx.state, ctx.types
    p = preset()
    v = util.mut(state.validators, index)
    v.withdrawal_credentials = (
        COMPOUNDING_WITHDRAWAL_PREFIX + bytes(v.withdrawal_credentials)[1:]
    )
    balance = state.balances[index]
    if balance > p.MIN_ACTIVATION_BALANCE:
        excess = balance - p.MIN_ACTIVATION_BALANCE
        state.balances[index] = p.MIN_ACTIVATION_BALANCE
        pd = types.PendingDeposit.default()
        pd.pubkey = bytes(v.pubkey)
        pd.withdrawal_credentials = bytes(v.withdrawal_credentials)
        pd.amount = excess
        pd.signature = G2_POINT_AT_INFINITY
        pd.slot = GENESIS_SLOT
        state.pending_deposits.append(pd)


def process_consolidation_request(ctx, request) -> None:
    state, cfg, types = ctx.state, ctx.cfg, ctx.types
    p = preset()
    pubkey2index = ctx.pubkey2index()
    source_pk = bytes(request.source_pubkey)
    target_pk = bytes(request.target_pubkey)
    cur = get_current_epoch(state)

    # switch-to-compounding self-request
    if source_pk == target_pk:
        index = pubkey2index.get(source_pk)
        if index is None:
            return
        v = state.validators[index]
        wc = bytes(v.withdrawal_credentials)
        if (
            has_eth1_withdrawal_credential(wc)
            and wc[12:] == bytes(request.source_address)
            and util.is_active_validator(v, cur)
            and v.exit_epoch == FAR_FUTURE_EPOCH
        ):
            switch_to_compounding_validator(ctx, index)
        return

    if len(state.pending_consolidations) == p.PENDING_CONSOLIDATIONS_LIMIT:
        return
    if util.get_consolidation_churn_limit(cfg, state) <= p.MIN_ACTIVATION_BALANCE:
        return
    source_index = pubkey2index.get(source_pk)
    target_index = pubkey2index.get(target_pk)
    if source_index is None or target_index is None:
        return
    source = state.validators[source_index]
    target = state.validators[target_index]
    swc = bytes(source.withdrawal_credentials)
    twc = bytes(target.withdrawal_credentials)
    if not (
        has_execution_withdrawal_credential(swc)
        and swc[12:] == bytes(request.source_address)
    ):
        return
    if not has_compounding_withdrawal_credential(twc):
        return
    if not (
        util.is_active_validator(source, cur)
        and util.is_active_validator(target, cur)
    ):
        return
    if (
        source.exit_epoch != FAR_FUTURE_EPOCH
        or target.exit_epoch != FAR_FUTURE_EPOCH
    ):
        return
    if cur < source.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD:
        return
    if get_pending_balance_to_withdraw(state, source_index) > 0:
        return
    source = util.mut(state.validators, source_index)
    source.exit_epoch = compute_consolidation_epoch_and_update_churn(
        cfg, state, source.effective_balance
    )
    source.withdrawable_epoch = (
        source.exit_epoch + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )
    pc = types.PendingConsolidation.default()
    pc.source_index = source_index
    pc.target_index = target_index
    state.pending_consolidations.append(pc)


# ---------------------------------------------------------------------------
# Operations driver + block entry
# ---------------------------------------------------------------------------


def process_operations(ctx, body) -> None:
    state = ctx.state
    p = preset()
    if ctx.fork_seq >= ForkSeq.electra:
        limit = min(
            state.eth1_data.deposit_count, state.deposit_requests_start_index
        )
        if state.eth1_deposit_index < limit:
            _req(
                len(body.deposits)
                == min(p.MAX_DEPOSITS, limit - state.eth1_deposit_index),
                "wrong deposit count",
            )
        else:
            _req(len(body.deposits) == 0, "deposits after transition")
    else:
        _req(
            len(body.deposits)
            == min(
                p.MAX_DEPOSITS,
                state.eth1_data.deposit_count - state.eth1_deposit_index,
            ),
            "wrong deposit count",
        )
    for op in body.proposer_slashings:
        process_proposer_slashing(ctx, op)
    for op in body.attester_slashings:
        process_attester_slashing(ctx, op)
    for op in body.attestations:
        process_attestation(ctx, op)
    for op in body.deposits:
        process_deposit(ctx, op)
    for op in body.voluntary_exits:
        process_voluntary_exit(ctx, op)
    if ctx.fork_seq >= ForkSeq.capella:
        for op in body.bls_to_execution_changes:
            process_bls_to_execution_change(ctx, op)
    if ctx.fork_seq >= ForkSeq.electra:
        for op in body.execution_requests.deposits:
            process_deposit_request(ctx, op)
        for op in body.execution_requests.withdrawals:
            process_withdrawal_request(ctx, op)
        for op in body.execution_requests.consolidations:
            process_consolidation_request(ctx, op)


def process_block(
    cfg,
    state,
    block,
    types,
    fork_seq: int,
    verify_signatures: bool = True,
    execution_engine=None,
) -> None:
    """Spec process_block for the given fork."""
    ctx = BlockCtx(cfg, state, types, fork_seq, verify_signatures)
    process_block_header(ctx, block)
    blinded = fork_seq >= ForkSeq.bellatrix and not hasattr(
        block.body, "execution_payload"
    )
    if fork_seq >= ForkSeq.capella:
        process_withdrawals(
            ctx,
            block.body.execution_payload_header
            if blinded
            else block.body.execution_payload,
        )
    if fork_seq >= ForkSeq.bellatrix and (
        fork_seq >= ForkSeq.capella or is_merge_transition_complete(ctx)
        or _has_execution_payload(ctx, block.body)
    ):
        process_execution_payload(ctx, block.body, execution_engine)
    process_randao(ctx, block.body)
    process_eth1_data(ctx, block.body)
    process_operations(ctx, block.body)
    if fork_seq >= ForkSeq.altair:
        process_sync_aggregate(ctx, block.body.sync_aggregate)


def _has_execution_payload(ctx, body) -> bool:
    """bellatrix is_execution_enabled: payload present (non-default) or
    merge already complete. Blinded bodies compare the header."""
    ns = ctx.types.by_fork[_fork_name(ctx.fork_seq)]
    if not hasattr(body, "execution_payload"):
        t = ns.ExecutionPayloadHeader
        return t.serialize(body.execution_payload_header) != t.serialize(
            t.default()
        )
    t = ns.ExecutionPayload
    return t.serialize(body.execution_payload) != t.serialize(t.default())

"""Epoch transition — numpy-vectorized per-validator processing.

Reference analog: packages/state-transition/src/epoch/index.ts:77 and
its 17 process* steps, plus the EpochTransitionCache precomputation
(src/cache/epochTransitionCache.ts). The reference already keeps
per-validator data as flat typed arrays for speed; here every step is
an array op over the registry (the tensor layout that later moves to
device, SURVEY.md §7 step 3). Follows ethereum/consensus-specs
{phase0,altair,capella,electra}/beacon-chain.md epoch processing.
"""

from __future__ import annotations

import numpy as np

from ..ssz.cached import SszVec
from ..params import (
    BASE_REWARDS_PER_EPOCH,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    GENESIS_SLOT,
    JUSTIFICATION_BITS_LENGTH,
    ForkSeq,
    preset,
)
from . import util
from .util import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    EpochShuffling,
    compute_activation_exit_epoch,
    compute_start_slot_at_epoch,
    get_block_root,
    get_current_epoch,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    increase_balance,
    initiate_validator_exit,
    initiate_validator_exit_electra,
    integer_squareroot,
)


class EpochTransitionCache:
    """Flat arrays shared by all steps of one epoch transition
    (reference: EpochTransitionCache, epochTransitionCache.ts)."""

    def __init__(self, cfg, state, fork_seq: int):
        self.cfg = cfg
        self.fork_seq = fork_seq
        p = preset()
        n = len(state.validators)
        self.n = n
        self.current_epoch = get_current_epoch(state)
        self.previous_epoch = get_previous_epoch(state)
        self.reg = util.RegistryArrays(state)
        self.balances = np.fromiter(state.balances, np.int64, n)
        self.active_prev = self.reg.is_active(self.previous_epoch)
        self.active_cur = self.reg.is_active(self.current_epoch)
        self.total_active_balance = max(
            p.EFFECTIVE_BALANCE_INCREMENT,
            int(self.reg.effective_balance[self.active_cur].sum()),
        )
        # eligible = active_prev | (slashed & prev+1 < withdrawable)
        self.eligible = self.active_prev | (
            self.reg.slashed
            & (self.previous_epoch + 1 < self.reg.withdrawable_epoch)
        )
        self.finality_delay = (
            self.previous_epoch - state.finalized_checkpoint.epoch
        )
        self.is_in_inactivity_leak = (
            self.finality_delay > p.MIN_EPOCHS_TO_INACTIVITY_PENALTY
        )

    def write_balances(self, state) -> None:
        state.balances[:] = [int(b) for b in self.balances]


# ---------------------------------------------------------------------------
# Participation extraction
# ---------------------------------------------------------------------------


def _participation_arrays(state):
    prev = np.fromiter(
        state.previous_epoch_participation, np.uint8, len(state.validators)
    )
    cur = np.fromiter(
        state.current_epoch_participation, np.uint8, len(state.validators)
    )
    return prev, cur


def _unslashed_participating(cache, participation, flag_index):
    return (
        cache.active_prev
        & ~cache.reg.slashed
        & ((participation >> flag_index) & 1).astype(bool)
    )


def _phase0_attesting_masks(cache, state):
    """Boolean masks over validators for phase0 matching source/target/
    head of the PREVIOUS epoch, plus per-validator best inclusion
    (delay, proposer) for the inclusion-delay reward. Memoized on the
    cache — both justification and rewards need it."""
    if hasattr(cache, "_phase0_masks"):
        return cache._phase0_masks
    n = cache.n
    src = np.zeros(n, bool)
    tgt = np.zeros(n, bool)
    head = np.zeros(n, bool)
    best_delay = np.full(n, np.iinfo(np.int64).max, np.int64)
    best_proposer = np.full(n, -1, np.int64)

    shuffling = util.get_shuffling(state, cache.previous_epoch)
    target_root = get_block_root(state, cache.previous_epoch)
    for att in state.previous_epoch_attestations:
        data = att.data
        committee = shuffling.committee(data.slot, data.index)
        bits = np.asarray(att.aggregation_bits, bool)
        attesters = committee[bits[: len(committee)]]
        src[attesters] = True
        better = att.inclusion_delay < best_delay[attesters]
        upd = attesters[better]
        best_delay[upd] = att.inclusion_delay
        best_proposer[upd] = att.proposer_index
        if data.target.root == target_root:
            tgt[attesters] = True
            try:
                head_root = util.get_block_root_at_slot(state, data.slot)
            except ValueError:
                head_root = None
            if head_root is not None and data.beacon_block_root == head_root:
                head[attesters] = True
    cache._phase0_masks = (src, tgt, head, best_delay, best_proposer)
    return cache._phase0_masks


# ---------------------------------------------------------------------------
# Justification & finalization
# ---------------------------------------------------------------------------


def _weigh_justification_and_finalization(
    state, total_active, prev_target, cur_target, types
):
    previous_epoch = get_previous_epoch(state)
    current_epoch = get_current_epoch(state)
    old_prev_justified = state.previous_justified_checkpoint
    old_cur_justified = state.current_justified_checkpoint
    Checkpoint = types.Checkpoint

    state.previous_justified_checkpoint = old_cur_justified
    bits = list(state.justification_bits)
    bits = [False] + bits[: JUSTIFICATION_BITS_LENGTH - 1]
    if prev_target * 3 >= total_active * 2:
        cp = Checkpoint.default()
        cp.epoch = previous_epoch
        cp.root = get_block_root(state, previous_epoch)
        state.current_justified_checkpoint = cp
        bits[1] = True
    if cur_target * 3 >= total_active * 2:
        cp = Checkpoint.default()
        cp.epoch = current_epoch
        cp.root = get_block_root(state, current_epoch)
        state.current_justified_checkpoint = cp
        bits[0] = True
    state.justification_bits = bits

    # finalization rules
    if all(bits[1:4]) and old_prev_justified.epoch + 3 == current_epoch:
        state.finalized_checkpoint = old_prev_justified
    if all(bits[1:3]) and old_prev_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_prev_justified
    if all(bits[0:3]) and old_cur_justified.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_cur_justified
    if all(bits[0:2]) and old_cur_justified.epoch + 1 == current_epoch:
        state.finalized_checkpoint = old_cur_justified


def process_justification_and_finalization(cache, state, types) -> None:
    if cache.current_epoch <= GENESIS_EPOCH + 1:
        return
    eb = cache.reg.effective_balance
    p = preset()
    if cache.fork_seq >= ForkSeq.altair:
        prev_part, cur_part = _participation_arrays(state)
        prev_mask = _unslashed_participating(
            cache, prev_part, TIMELY_TARGET_FLAG_INDEX
        )
        cur_mask = (
            cache.active_cur
            & ~cache.reg.slashed
            & ((cur_part >> TIMELY_TARGET_FLAG_INDEX) & 1).astype(bool)
        )
        prev_target = max(
            p.EFFECTIVE_BALANCE_INCREMENT, int(eb[prev_mask].sum())
        )
        cur_target = max(
            p.EFFECTIVE_BALANCE_INCREMENT, int(eb[cur_mask].sum())
        )
    else:
        src, tgt, head, _, _ = _phase0_attesting_masks(cache, state)
        prev_target = max(
            p.EFFECTIVE_BALANCE_INCREMENT,
            int(eb[tgt & ~cache.reg.slashed].sum()),
        )
        # current-epoch target attesters. At the epoch's first slot the
        # epoch-start root is not in state yet (unrealized mid-epoch
        # computation) — then no current-epoch attestation can have been
        # included either, so the balance is zero.
        cur_tgt = np.zeros(cache.n, bool)
        shuffling = util.get_shuffling(state, cache.current_epoch)
        try:
            cur_target_root = get_block_root(state, cache.current_epoch)
        except ValueError:
            cur_target_root = None
        for att in state.current_epoch_attestations:
            if att.data.target.root != cur_target_root:
                continue
            committee = shuffling.committee(att.data.slot, att.data.index)
            bits = np.asarray(att.aggregation_bits, bool)
            cur_tgt[committee[bits[: len(committee)]]] = True
        cur_target = max(
            p.EFFECTIVE_BALANCE_INCREMENT,
            int(eb[cur_tgt & ~cache.reg.slashed].sum()),
        )
    _weigh_justification_and_finalization(
        state, cache.total_active_balance, prev_target, cur_target, types
    )


# ---------------------------------------------------------------------------
# Inactivity scores (altair+)
# ---------------------------------------------------------------------------


def process_inactivity_updates(cache, state) -> None:
    if cache.current_epoch == GENESIS_EPOCH:
        return
    cfg = cache.cfg
    n = cache.n
    scores = np.fromiter(state.inactivity_scores, np.int64, n)
    prev_part, _ = _participation_arrays(state)
    target_mask = _unslashed_participating(
        cache, prev_part, TIMELY_TARGET_FLAG_INDEX
    )
    el = cache.eligible
    scores = np.where(
        el & target_mask, scores - np.minimum(1, scores), scores
    )
    scores = np.where(
        el & ~target_mask, scores + cfg.INACTIVITY_SCORE_BIAS, scores
    )
    if not cache.is_in_inactivity_leak:
        scores = np.where(
            el,
            scores - np.minimum(cfg.INACTIVITY_SCORE_RECOVERY_RATE, scores),
            scores,
        )
    state.inactivity_scores[:] = [int(s) for s in scores]


# ---------------------------------------------------------------------------
# Rewards & penalties
# ---------------------------------------------------------------------------


def _inactivity_penalty_quotient(fork_seq: int) -> int:
    p = preset()
    if fork_seq >= ForkSeq.bellatrix:
        return p.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    if fork_seq >= ForkSeq.altair:
        return p.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    return p.INACTIVITY_PENALTY_QUOTIENT


def process_rewards_and_penalties(cache, state) -> None:
    if cache.current_epoch == GENESIS_EPOCH:
        return
    if cache.fork_seq >= ForkSeq.altair:
        rewards, penalties = _altair_deltas(cache, state)
    else:
        rewards, penalties = _phase0_deltas(cache, state)
    cache.balances = np.maximum(0, cache.balances + rewards - penalties)
    cache.write_balances(state)


def _altair_deltas(cache, state):
    p = preset()
    n = cache.n
    eb = cache.reg.effective_balance
    increments = eb // p.EFFECTIVE_BALANCE_INCREMENT
    base_reward_per_increment = (
        p.EFFECTIVE_BALANCE_INCREMENT
        * p.BASE_REWARD_FACTOR
        // integer_squareroot(cache.total_active_balance)
    )
    base_reward = increments * base_reward_per_increment
    active_increments = (
        cache.total_active_balance // p.EFFECTIVE_BALANCE_INCREMENT
    )
    prev_part, _ = _participation_arrays(state)
    rewards = np.zeros(n, np.int64)
    penalties = np.zeros(n, np.int64)
    el = cache.eligible
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        mask = _unslashed_participating(cache, prev_part, flag_index)
        participating_increments = int(increments[mask].sum())
        if not cache.is_in_inactivity_leak:
            reward = (
                base_reward * weight * participating_increments
                // (active_increments * WEIGHT_DENOMINATOR)
            )
            rewards += np.where(el & mask, reward, 0)
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties += np.where(
                el & ~mask, base_reward * weight // WEIGHT_DENOMINATOR, 0
            )
    # inactivity penalties
    target_mask = _unslashed_participating(
        cache, prev_part, TIMELY_TARGET_FLAG_INDEX
    )
    scores = np.fromiter(state.inactivity_scores, np.int64, n)
    quotient = (
        cache.cfg.INACTIVITY_SCORE_BIAS
        * _inactivity_penalty_quotient(cache.fork_seq)
    )
    penalties += np.where(el & ~target_mask, eb * scores // quotient, 0)
    return rewards, penalties


def _phase0_deltas(cache, state):
    p = preset()
    n = cache.n
    eb = cache.reg.effective_balance
    total = cache.total_active_balance
    sqrt_total = integer_squareroot(total)
    base_reward = (
        eb * p.BASE_REWARD_FACTOR // sqrt_total // BASE_REWARDS_PER_EPOCH
    )
    proposer_reward = base_reward // p.PROPOSER_REWARD_QUOTIENT

    src, tgt, head, best_delay, best_proposer = _phase0_attesting_masks(
        cache, state
    )
    unsl = ~cache.reg.slashed
    src, tgt, head = src & unsl, tgt & unsl, head & unsl
    el = cache.eligible
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    total_increments = total // increment

    rewards = np.zeros(n, np.int64)
    penalties = np.zeros(n, np.int64)
    for mask in (src, tgt, head):
        attesting_balance = max(increment, int(eb[mask].sum()))
        attesting_increments = attesting_balance // increment
        if cache.is_in_inactivity_leak:
            rewards += np.where(el & mask, base_reward, 0)
        else:
            rewards += np.where(
                el & mask,
                base_reward * attesting_increments // total_increments,
                0,
            )
        penalties += np.where(el & ~mask, base_reward, 0)

    # inclusion-delay rewards (proposer + attester), source attesters only
    max_attester_reward = base_reward - proposer_reward
    for i in np.nonzero(src)[0]:
        d = int(best_delay[i])
        if d == np.iinfo(np.int64).max:
            continue
        rewards[int(best_proposer[i])] += int(proposer_reward[i])
        rewards[i] += int(max_attester_reward[i]) // d

    # inactivity leak quadratic penalties
    if cache.is_in_inactivity_leak:
        penalties += np.where(
            el, BASE_REWARDS_PER_EPOCH * base_reward - proposer_reward, 0
        )
        penalties += np.where(
            el & ~tgt,
            eb * cache.finality_delay // p.INACTIVITY_PENALTY_QUOTIENT,
            0,
        )
    return rewards, penalties


# ---------------------------------------------------------------------------
# Registry updates
# ---------------------------------------------------------------------------


def process_registry_updates(cache, state) -> None:
    """Vectorized over the RegistryArrays columns: the candidate sets
    (activation-queue entrants, ejections, activations) are tiny every
    epoch, so the per-validator Python loop — measured 4.3 s of the
    8 s 1M-validator epoch transition — reduces to numpy masks plus a
    loop over only the selected indices. The masks read the
    PRE-transition columns, which matches the spec's sequencing:
    validators marked eligible in this pass get eligibility epoch
    current+1 > finalized epoch, so they can never also activate in
    this pass (epochProcessing registry_updates)."""
    cfg = cache.cfg
    current_epoch = cache.current_epoch
    electra = cache.fork_seq >= ForkSeq.electra
    activation_epoch = compute_activation_exit_epoch(current_epoch)
    ra = cache.reg
    p = preset()
    FARC = 2**63 - 1  # RegistryArrays' FAR_FUTURE_EPOCH clamp

    elig_far = ra.activation_eligibility_epoch >= FARC
    if electra:
        queue_mask = elig_far & (
            ra.effective_balance >= p.MIN_ACTIVATION_BALANCE
        )
    else:
        queue_mask = elig_far & (
            ra.effective_balance == p.MAX_EFFECTIVE_BALANCE
        )
    eject_mask = (
        ~queue_mask
        & ra.is_active(current_epoch)
        & (ra.effective_balance <= cfg.EJECTION_BALANCE)
    )
    fin_epoch = int(state.finalized_checkpoint.epoch)
    activate_mask = (ra.activation_eligibility_epoch <= fin_epoch) & (
        ra.activation_epoch >= FARC
    )

    for index in np.nonzero(queue_mask)[0]:
        util.mut(state.validators, int(index)).activation_eligibility_epoch = (
            current_epoch + 1
        )
    for index in np.nonzero(eject_mask)[0]:
        if electra:
            initiate_validator_exit_electra(cfg, state, int(index))
        else:
            initiate_validator_exit(cfg, state, int(index))
    if electra:
        for index in np.nonzero(activate_mask)[0]:
            util.mut(state.validators, int(index)).activation_epoch = (
                activation_epoch
            )
    else:
        cand = np.nonzero(activate_mask)[0]
        order = np.lexsort(
            (cand, ra.activation_eligibility_epoch[cand])
        )
        if cache.fork_seq >= ForkSeq.deneb:
            churn = util.get_validator_activation_churn_limit(cfg, state)
        else:
            churn = util.get_validator_churn_limit(cfg, state)
        for i in cand[order][:churn]:
            util.mut(state.validators, int(i)).activation_epoch = (
                activation_epoch
            )


# ---------------------------------------------------------------------------
# Slashings
# ---------------------------------------------------------------------------


def process_slashings(cache, state) -> None:
    p = preset()
    epoch = cache.current_epoch
    total = cache.total_active_balance
    if cache.fork_seq >= ForkSeq.bellatrix:
        multiplier = p.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX
    elif cache.fork_seq >= ForkSeq.altair:
        multiplier = p.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    else:
        multiplier = p.PROPORTIONAL_SLASHING_MULTIPLIER
    adjusted = min(sum(state.slashings) * multiplier, total)
    increment = p.EFFECTIVE_BALANCE_INCREMENT

    target_epoch = epoch + p.EPOCHS_PER_SLASHINGS_VECTOR // 2
    mask = cache.reg.slashed & (cache.reg.withdrawable_epoch == target_epoch)
    idxs = np.nonzero(mask)[0]
    if cache.fork_seq >= ForkSeq.electra:
        penalty_per_increment = adjusted // (total // increment)
        for i in idxs:
            eff_increments = int(cache.reg.effective_balance[i]) // increment
            penalty = eff_increments * penalty_per_increment
            util.decrease_balance(state, int(i), penalty)
    else:
        for i in idxs:
            numerator = (
                int(cache.reg.effective_balance[i]) // increment * adjusted
            )
            penalty = numerator // total * increment
            util.decrease_balance(state, int(i), penalty)
    if len(idxs):
        cache.balances = np.fromiter(state.balances, np.int64, cache.n)


# ---------------------------------------------------------------------------
# Electra: pending deposits / consolidations
# ---------------------------------------------------------------------------


def process_pending_deposits(cache, state, types) -> None:
    from .block import add_validator_to_registry, is_valid_deposit_signature

    cfg = cache.cfg
    p = preset()
    next_epoch = cache.current_epoch + 1
    available = state.deposit_balance_to_consume + util.get_activation_exit_churn_limit(
        cfg, state
    )
    processed_amount = 0
    next_deposit_index = 0
    postponed = []
    churn_reached = False
    finalized_slot = compute_start_slot_at_epoch(
        state.finalized_checkpoint.epoch
    )
    pubkey2index = util.PubkeyIndexView(state)

    for dep in state.pending_deposits:
        if (
            dep.slot > GENESIS_SLOT
            and state.eth1_deposit_index < state.deposit_requests_start_index
        ):
            break
        if dep.slot > finalized_slot:
            break
        if next_deposit_index >= p.MAX_PENDING_DEPOSITS_PER_EPOCH:
            break
        idx = pubkey2index.get(bytes(dep.pubkey))
        is_exited = False
        is_withdrawn = False
        if idx is not None:
            v = state.validators[idx]
            is_exited = v.exit_epoch < FAR_FUTURE_EPOCH
            is_withdrawn = v.withdrawable_epoch < next_epoch
        if is_withdrawn:
            _apply_pending_deposit(cfg, state, dep, pubkey2index, types)
        elif is_exited:
            postponed.append(dep)
        else:
            churn_reached = processed_amount + dep.amount > available
            if churn_reached:
                break
            processed_amount += dep.amount
            _apply_pending_deposit(cfg, state, dep, pubkey2index, types)
        next_deposit_index += 1

    state.pending_deposits = SszVec(
        list(state.pending_deposits[next_deposit_index:]) + postponed
    )
    state.deposit_balance_to_consume = (
        available - processed_amount if churn_reached else 0
    )


def _apply_pending_deposit(cfg, state, dep, pubkey2index, types) -> None:
    from .block import add_validator_to_registry, is_valid_deposit_signature

    idx = pubkey2index.get(bytes(dep.pubkey))
    if idx is None:
        if is_valid_deposit_signature(
            cfg,
            dep.pubkey,
            dep.withdrawal_credentials,
            dep.amount,
            dep.signature,
            types,
        ):
            add_validator_to_registry(
                cfg,
                state,
                dep.pubkey,
                dep.withdrawal_credentials,
                dep.amount,
                types,
                fork_seq=ForkSeq.electra,
            )
            pubkey2index[bytes(dep.pubkey)] = len(state.validators) - 1
    else:
        increase_balance(state, idx, dep.amount)


def process_pending_consolidations(cache, state) -> None:
    next_epoch = cache.current_epoch + 1
    done = 0
    for pc in state.pending_consolidations:
        source = state.validators[pc.source_index]
        if source.slashed:
            done += 1
            continue
        if source.withdrawable_epoch > next_epoch:
            break
        amount = min(
            state.balances[pc.source_index], source.effective_balance
        )
        util.decrease_balance(state, pc.source_index, amount)
        increase_balance(state, pc.target_index, amount)
        done += 1
    state.pending_consolidations = SszVec(state.pending_consolidations[done:])


# ---------------------------------------------------------------------------
# Final housekeeping steps
# ---------------------------------------------------------------------------


def process_eth1_data_reset(cache, state) -> None:
    p = preset()
    next_epoch = cache.current_epoch + 1
    if next_epoch % p.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        state.eth1_data_votes = SszVec()


def process_effective_balance_updates(cache, state) -> None:
    from .block import has_compounding_withdrawal_credential

    p = preset()
    hysteresis_increment = (
        p.EFFECTIVE_BALANCE_INCREMENT // p.HYSTERESIS_QUOTIENT
    )
    down = hysteresis_increment * p.HYSTERESIS_DOWNWARD_MULTIPLIER
    up = hysteresis_increment * p.HYSTERESIS_UPWARD_MULTIPLIER
    electra = cache.fork_seq >= ForkSeq.electra
    for index, v in enumerate(state.validators):
        balance = state.balances[index]
        if electra:
            max_eb = (
                p.MAX_EFFECTIVE_BALANCE_ELECTRA
                if has_compounding_withdrawal_credential(
                    v.withdrawal_credentials
                )
                else p.MIN_ACTIVATION_BALANCE
            )
        else:
            max_eb = p.MAX_EFFECTIVE_BALANCE
        if (
            balance + down < v.effective_balance
            or v.effective_balance + up < balance
        ):
            util.mut(state.validators, index).effective_balance = min(
                balance - balance % p.EFFECTIVE_BALANCE_INCREMENT, max_eb
            )


def process_slashings_reset(cache, state) -> None:
    p = preset()
    next_epoch = cache.current_epoch + 1
    state.slashings[next_epoch % p.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(cache, state) -> None:
    p = preset()
    next_epoch = cache.current_epoch + 1
    state.randao_mixes[next_epoch % p.EPOCHS_PER_HISTORICAL_VECTOR] = (
        get_randao_mix(state, cache.current_epoch)
    )


def process_historical_roots_update(cache, state, types) -> None:
    """phase0..bellatrix: append HistoricalBatch root."""
    p = preset()
    next_epoch = cache.current_epoch + 1
    if next_epoch % (p.SLOTS_PER_HISTORICAL_ROOT // p.SLOTS_PER_EPOCH) == 0:
        batch = types.HistoricalBatch.default()
        batch.block_roots = list(state.block_roots)
        batch.state_roots = list(state.state_roots)
        state.historical_roots.append(
            types.HistoricalBatch.hash_tree_root(batch)
        )


def process_historical_summaries_update(cache, state, types) -> None:
    """capella+: append HistoricalSummary (detached roots, EIP-4895)."""
    from ..ssz import VectorType, Root

    p = preset()
    next_epoch = cache.current_epoch + 1
    if next_epoch % (p.SLOTS_PER_HISTORICAL_ROOT // p.SLOTS_PER_EPOCH) == 0:
        roots_t = VectorType(Root, p.SLOTS_PER_HISTORICAL_ROOT)
        summary = types.HistoricalSummary.default()
        summary.block_summary_root = roots_t.hash_tree_root(
            list(state.block_roots)
        )
        summary.state_summary_root = roots_t.hash_tree_root(
            list(state.state_roots)
        )
        state.historical_summaries.append(summary)


def process_participation_record_updates(cache, state) -> None:
    state.previous_epoch_attestations = SszVec(
        state.current_epoch_attestations
    )
    state.current_epoch_attestations = SszVec()


def process_participation_flag_updates(cache, state) -> None:
    state.previous_epoch_participation = SszVec(
        state.current_epoch_participation
    )
    state.current_epoch_participation = SszVec([0] * len(state.validators))


def process_sync_committee_updates(cache, state, types) -> None:
    from ..crypto.bls.signature import aggregate_pubkeys

    p = preset()
    next_epoch = cache.current_epoch + 1
    if next_epoch % p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD != 0:
        return
    state.current_sync_committee = state.next_sync_committee
    indices = util.get_next_sync_committee_indices(
        state, electra=cache.fork_seq >= ForkSeq.electra
    )
    pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
    sc = types.SyncCommittee.default()
    sc.pubkeys = pubkeys
    sc.aggregate_pubkey = aggregate_pubkeys(pubkeys)
    state.next_sync_committee = sc


def compute_unrealized_checkpoints(cfg, state, types, fork_seq: int):
    """What (justified, finalized) WOULD become if the epoch ended now —
    the fork-choice 'unrealized' checkpoints (reference:
    computeUnrealizedCheckpoints, fork-choice onBlock pull-up). Runs the
    justification step on the live state and restores the mutated
    fields."""
    snapshot = (
        state.previous_justified_checkpoint,
        state.current_justified_checkpoint,
        state.finalized_checkpoint,
        list(state.justification_bits),
    )
    cache = EpochTransitionCache(cfg, state, fork_seq)
    process_justification_and_finalization(cache, state, types)
    uj = state.current_justified_checkpoint
    uf = state.finalized_checkpoint
    (
        state.previous_justified_checkpoint,
        state.current_justified_checkpoint,
        state.finalized_checkpoint,
        state.justification_bits,
    ) = snapshot
    return uj, uf


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------


def process_epoch(cfg, state, types, fork_seq: int) -> None:
    """Run the full epoch transition for the given fork's state."""
    cache = EpochTransitionCache(cfg, state, fork_seq)
    process_justification_and_finalization(cache, state, types)
    if fork_seq >= ForkSeq.altair:
        process_inactivity_updates(cache, state)
    process_rewards_and_penalties(cache, state)
    process_registry_updates(cache, state)
    process_slashings(cache, state)
    process_eth1_data_reset(cache, state)
    if fork_seq >= ForkSeq.electra:
        process_pending_deposits(cache, state, types)
        process_pending_consolidations(cache, state)
    process_effective_balance_updates(cache, state)
    process_slashings_reset(cache, state)
    process_randao_mixes_reset(cache, state)
    if fork_seq >= ForkSeq.capella:
        process_historical_summaries_update(cache, state, types)
    else:
        process_historical_roots_update(cache, state, types)
    if fork_seq >= ForkSeq.altair:
        process_participation_flag_updates(cache, state)
        process_sync_committee_updates(cache, state, types)
    else:
        process_participation_record_updates(cache, state)

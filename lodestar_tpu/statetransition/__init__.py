"""Consensus core: the pure (no-I/O) beacon state transition.

Reference analog: packages/state-transition (SURVEY.md §2.5) —
stateTransition/processSlots/processBlock/processEpoch over cached
beacon states, per-fork upgrades, and spec helpers. Per-validator work
is numpy-vectorized (registry as struct-of-arrays), the layout that
later moves onto the TPU.
"""

from .block import BlockProcessError, process_block
from .epoch import process_epoch
from .genesis import (
    create_interop_genesis_state,
    interop_pubkeys,
    interop_secret_key,
)
from .slot import (
    BeaconStateView,
    fork_at_epoch,
    process_slots,
    state_transition,
    upgrade_to_altair,
    upgrade_to_bellatrix,
    upgrade_to_capella,
    upgrade_to_deneb,
    upgrade_to_electra,
    verify_block_signature,
)
from . import util

__all__ = [
    "BeaconStateView",
    "BlockProcessError",
    "create_interop_genesis_state",
    "fork_at_epoch",
    "interop_pubkeys",
    "interop_secret_key",
    "process_block",
    "process_epoch",
    "process_slots",
    "state_transition",
    "upgrade_to_altair",
    "upgrade_to_bellatrix",
    "upgrade_to_capella",
    "upgrade_to_deneb",
    "upgrade_to_electra",
    "util",
    "verify_block_signature",
]

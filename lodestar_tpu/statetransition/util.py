"""Spec helper functions for the beacon state transition.

Reference analog: packages/state-transition/src/util/ (epoch.ts,
seed.ts, shuffle.ts, committee.ts, balance.ts, validator.ts,
domain.ts, aggregator.ts) following ethereum/consensus-specs
beacon-chain.md helpers. Per-validator loops are numpy-vectorized —
the registry is tensor-shaped data (SURVEY.md §7 step 3), which is
exactly what makes the epoch transition map onto the TPU later.
"""

from __future__ import annotations

import functools
from hashlib import sha256

import numpy as np

from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_SYNC_COMMITTEE,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    preset,
)


def mut(vec, index: int):
    """Copy-on-write element access for mutation.

    State clones share flat-container list elements (Validator etc.,
    ssz/cached.py clone_value); shared elements are frozen against
    in-place writes. Writers fetch through this helper: it replaces a
    shared element with a private copy (marking the list slot dirty for
    the incremental hasher) and returns the writable object.
    """
    v = vec[index]
    if getattr(v, "_shared", False):
        v = v.copy()
        vec[index] = v
    elif hasattr(vec, "note_cols"):
        # already-private element mutated in place: the columnar
        # registry cache must still see the row as stale
        vec.note_cols(index)
    return v


class PubkeyIndexView:
    """pubkey(48B) -> validator index map shared across states.

    Reference analog: @chainsafe/pubkey-index-map + Index2PubkeyCache
    (state-transition/src/cache/pubkeyCache.ts:50-69) — one process-wide
    append-only map instead of a dict rebuilt per block (VERDICT r1
    weak #6). Registration progress is tracked PER VALIDATORS LIST (a
    watermark carried on the SszVec and propagated through clones), so
    every fork registers its own appends even when another fork of the
    same chain grew first. A guarded get() verifies the binding against
    the live registry, falling back to a linear scan only on actual
    cross-fork index divergence (two forks binding one pubkey to
    different indices — requires conflicting unfinalized deposits).
    """

    _maps: dict[bytes, dict[bytes, int]] = {}  # per genesis_validators_root

    def __init__(self, state):
        key = bytes(state.genesis_validators_root)
        self._state = state
        self.map = self._maps.setdefault(key, {})
        self._sync()

    def _sync(self) -> None:
        vals = self._state.validators
        start = getattr(vals, "_aux", None)
        if not isinstance(start, int) or start > len(vals):
            start = 0
        if start < len(vals):
            m = self.map
            for i in range(start, len(vals)):
                m.setdefault(bytes(vals[i].pubkey), i)
        try:
            vals._aux = len(vals)
        except AttributeError:
            pass  # plain list: re-registers each sync (correct, slower)

    def get(self, pubkey: bytes):
        self._sync()
        vals = self._state.validators
        i = self.map.get(pubkey)
        if i is not None and i < len(vals) and bytes(vals[i].pubkey) == pubkey:
            return i
        if i is None:
            # every index of this registry is registered (watermark), so
            # an absent key is truly absent from this state
            return None
        # fork divergence: this fork bound the index differently
        return next(
            (j for j, v in enumerate(vals) if bytes(v.pubkey) == pubkey),
            None,
        )

    def __getitem__(self, pubkey: bytes) -> int:
        i = self.get(pubkey)
        if i is None:
            raise KeyError(pubkey.hex())
        return i

    def __contains__(self, pubkey: bytes) -> bool:
        return self.get(pubkey) is not None

    def __setitem__(self, pubkey: bytes, index: int) -> None:
        self.map.setdefault(pubkey, index)


def hash32(data: bytes) -> bytes:
    return sha256(data).digest()


def integer_squareroot(n: int) -> int:
    """Largest x with x*x <= n (spec integer_squareroot)."""
    import math

    return math.isqrt(n)


def uint_to_bytes8(n: int) -> bytes:
    return int(n).to_bytes(8, "little")


# ---------------------------------------------------------------------------
# Epoch / slot math
# ---------------------------------------------------------------------------


def compute_epoch_at_slot(slot: int) -> int:
    return slot // preset().SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(epoch: int) -> int:
    return epoch * preset().SLOTS_PER_EPOCH


def compute_activation_exit_epoch(epoch: int) -> int:
    return epoch + 1 + preset().MAX_SEED_LOOKAHEAD


def get_current_epoch(state) -> int:
    return compute_epoch_at_slot(state.slot)


def get_previous_epoch(state) -> int:
    cur = get_current_epoch(state)
    return GENESIS_EPOCH if cur == GENESIS_EPOCH else cur - 1


def get_randao_mix(state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % preset().EPOCHS_PER_HISTORICAL_VECTOR]


def get_block_root_at_slot(state, slot: int) -> bytes:
    if not (slot < state.slot <= slot + preset().SLOTS_PER_HISTORICAL_ROOT):
        raise ValueError(f"slot {slot} out of block_roots window at {state.slot}")
    return state.block_roots[slot % preset().SLOTS_PER_HISTORICAL_ROOT]


def get_block_root(state, epoch: int) -> bytes:
    return get_block_root_at_slot(state, compute_start_slot_at_epoch(epoch))


# ---------------------------------------------------------------------------
# Validator predicates (scalar + vectorized)
# ---------------------------------------------------------------------------


def is_active_validator(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


def is_eligible_for_activation_queue(v, fork_seq: int = 0) -> bool:
    from ..params import ForkSeq

    p = preset()
    if fork_seq >= ForkSeq.electra:
        return (
            v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance >= p.MIN_ACTIVATION_BALANCE
        )
    return (
        v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
        and v.effective_balance == p.MAX_EFFECTIVE_BALANCE
    )


def is_eligible_for_activation(state, v) -> bool:
    return (
        v.activation_eligibility_epoch <= state.finalized_checkpoint.epoch
        and v.activation_epoch == FAR_FUTURE_EPOCH
    )


def is_slashable_validator(v, epoch: int) -> bool:
    return (not v.slashed) and v.activation_epoch <= epoch < v.withdrawable_epoch


def get_active_validator_indices(state, epoch: int) -> list[int]:
    return [
        i
        for i, v in enumerate(state.validators)
        if v.activation_epoch <= epoch < v.exit_epoch
    ]


class RegistryArrays:
    """Struct-of-arrays view of the validator registry — the tensor
    layout every epoch-processing step operates on (reference keeps
    effective balances as a flat Uint8Array for the same reason,
    state-transition/src/cache/effectiveBalanceIncrements.ts)."""

    _FIELDS = (
        "effective_balance",
        "slashed",
        "activation_eligibility_epoch",
        "activation_epoch",
        "exit_epoch",
        "withdrawable_epoch",
    )

    def __init__(self, state):
        vals = state.validators
        n = len(vals)
        self.n = n
        cached = getattr(vals, "_cols", None)
        dirty = getattr(vals, "_cols_dirty", None)
        if (
            isinstance(cached, dict)
            and cached.get("n") == n
            and dirty is not None
        ):
            cols = {k: cached[k] for k in self._FIELDS}
            if dirty:
                # refresh only mutated rows, copy-on-write so sibling
                # forks holding the old arrays stay consistent
                cols = {k: a.copy() for k, a in cols.items()}
                clampv = 2**63 - 1
                for i in dirty:
                    v = vals[i]
                    cols["effective_balance"][i] = v.effective_balance
                    cols["slashed"][i] = v.slashed
                    cols["activation_eligibility_epoch"][i] = min(
                        v.activation_eligibility_epoch, clampv
                    )
                    cols["activation_epoch"][i] = min(
                        v.activation_epoch, clampv
                    )
                    cols["exit_epoch"][i] = min(v.exit_epoch, clampv)
                    cols["withdrawable_epoch"][i] = min(
                        v.withdrawable_epoch, clampv
                    )
        else:
            cols = {
                "effective_balance": np.fromiter(
                    (v.effective_balance for v in vals), np.int64, n
                ),
                "slashed": np.fromiter(
                    (v.slashed for v in vals), np.bool_, n
                ),
                "activation_eligibility_epoch": np.fromiter(
                    (
                        min(v.activation_eligibility_epoch, 2**63 - 1)
                        for v in vals
                    ),
                    np.int64,
                    n,
                ),
                "activation_epoch": np.fromiter(
                    (min(v.activation_epoch, 2**63 - 1) for v in vals),
                    np.int64,
                    n,
                ),
                "exit_epoch": np.fromiter(
                    (min(v.exit_epoch, 2**63 - 1) for v in vals),
                    np.int64,
                    n,
                ),
                "withdrawable_epoch": np.fromiter(
                    (min(v.withdrawable_epoch, 2**63 - 1) for v in vals),
                    np.int64,
                    n,
                ),
            }
        try:
            vals._cols = {"n": n, **cols}
            vals._cols_dirty.clear()
        except AttributeError:
            pass  # plain list (tests): no cache to keep
        # consumers treat these columns as READ-ONLY views
        self.effective_balance = cols["effective_balance"]
        self.slashed = cols["slashed"]
        self.activation_eligibility_epoch = cols[
            "activation_eligibility_epoch"
        ]
        self.activation_epoch = cols["activation_epoch"]
        self.exit_epoch = cols["exit_epoch"]
        self.withdrawable_epoch = cols["withdrawable_epoch"]

    def is_active(self, epoch: int) -> np.ndarray:
        return (self.activation_epoch <= epoch) & (epoch < self.exit_epoch)


# ---------------------------------------------------------------------------
# Seeds and shuffling
# ---------------------------------------------------------------------------


def get_seed(state, epoch: int, domain_type: bytes) -> bytes:
    p = preset()
    mix = get_randao_mix(
        state, epoch + p.EPOCHS_PER_HISTORICAL_VECTOR - p.MIN_SEED_LOOKAHEAD - 1
    )
    return hash32(domain_type + uint_to_bytes8(epoch) + mix)


def compute_shuffled_index(index: int, count: int, seed: bytes) -> int:
    """Scalar spec swap-or-not (for spot checks); the batch path is
    compute_shuffling()."""
    assert index < count
    p = preset()
    for r in range(p.SHUFFLE_ROUND_COUNT):
        pivot = (
            int.from_bytes(hash32(seed + bytes([r]))[:8], "little") % count
        )
        flip = (pivot + count - index) % count
        position = max(index, flip)
        source = hash32(
            seed + bytes([r]) + (position // 256).to_bytes(4, "little")
        )
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) % 2:
            index = flip
    return index


_SHUFFLE_PAD = 65536  # shape bucket: bounds XLA recompiles per size


@functools.lru_cache(maxsize=4)
def _shuffle_rounds_jit(padded: int, rounds: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(idx0, pivots, blocks_flat, count):
        def body(r, idx):
            pivot = pivots[r]
            flip = pivot - idx
            flip = jnp.where(flip < 0, flip + count, flip)
            position = jnp.maximum(idx, flip)
            byte = blocks_flat[
                r, ((position >> 8) << 5) + ((position & 255) >> 3)
            ]
            bit = (byte >> (position & 7).astype(jnp.uint8)) & 1
            return jnp.where(bit == 1, flip, idx)

        return jax.lax.fori_loop(0, rounds, body, idx0)

    return run


def _shuffle_rounds_xla(count: int, seed: bytes, blocks_all):
    """All SHUFFLE_ROUND_COUNT swap-or-not rounds as ONE jitted XLA
    program (fused elementwise + gathers; runs on the TPU when it is
    the default backend — the device-side epoch-boundary path). Shapes
    are padded to _SHUFFLE_PAD buckets so churn-driven active-count
    changes don't recompile. Returns None when JAX is unavailable."""
    try:
        import jax.numpy as jnp
    except Exception:  # pragma: no cover
        return None
    p = preset()
    rounds = p.SHUFFLE_ROUND_COUNT
    padded = -(-count // _SHUFFLE_PAD) * _SHUFFLE_PAD
    # pad lanes run with idx=0: their gathers stay in range (flip and
    # position are < count) and their results are discarded
    idx0 = jnp.asarray(
        np.pad(np.arange(count, dtype=np.int32), (0, padded - count))
    )
    pivots = np.array(
        [
            int.from_bytes(hash32(seed + bytes([r]))[:8], "little")
            % count
            for r in range(rounds)
        ],
        np.int32,
    )
    out = _shuffle_rounds_jit(padded, rounds)(
        idx0,
        jnp.asarray(pivots),
        jnp.asarray(blocks_all.reshape(rounds, -1)),
        jnp.int32(count),
    )
    return np.asarray(out)[:count].astype(np.int64)


def compute_shuffling(count: int, seed: bytes) -> np.ndarray:
    """Vectorized swap-or-not over all indices at once: shuffled[i] is
    where index i lands (equals compute_shuffled_index(i) for all i).

    Each of the SHUFFLE_ROUND_COUNT rounds is one numpy pass: pivot from
    the round hash, per-position decision bytes from vectorized SHA-256
    over the position blocks. Reference analog:
    @chainsafe/swap-or-not-shuffle native addon (SURVEY.md §2.1) — here
    the rounds are data-parallel array ops, the natural TPU layout.
    """
    if count == 0:
        return np.zeros(0, np.int64)
    p = preset()
    if count >= 2**31:
        # int64 reference path BEFORE the int32 XLA fast path, which
        # would overflow (VALIDATOR_REGISTRY_LIMIT is 2^40)
        return _compute_shuffling_int64(count, seed, None)
    idx = np.arange(count, dtype=np.int64)
    n_blocks = (count + 255) // 256
    rounds = p.SHUFFLE_ROUND_COUNT
    # ALL decision hashes of ALL rounds in one native batched SHA-256
    # call (seed||round||block_le4, 37 bytes each): at 1M validators
    # that's 90 x 3907 hashes — a per-block hashlib loop here was 95%
    # of the measured 37 s full-registry shuffle (round-4 scale work).
    blocks_all = None
    try:
        from ..crypto import sha256_batch as _sb

        if _sb.available():
            # message matrix built vectorized (a bytes-join generator
            # here measured 2 s at 1M validators)
            msgs = np.zeros((rounds, n_blocks, 37), np.uint8)
            msgs[:, :, :32] = np.frombuffer(seed, np.uint8)
            msgs[:, :, 32] = np.arange(rounds, dtype=np.uint8)[:, None]
            msgs[:, :, 33:37] = (
                np.arange(n_blocks, dtype=np.uint32)
                .view(np.uint8)
                .reshape(n_blocks, 4)[None, :, :]
            )
            digests = _sb.hash_small_batch(msgs.tobytes(), 37)
            blocks_all = np.frombuffer(digests, np.uint8).reshape(
                rounds, n_blocks, 32
            )
    except Exception:
        blocks_all = None
    if blocks_all is not None:
        fast = _shuffle_rounds_xla(count, seed, blocks_all)
        if fast is not None:
            return fast
    # int32 lanes + branch-free bit ops per round. VALIDATOR_REGISTRY_
    # LIMIT is 2^40, so int32 is NOT spec-guaranteed — registries
    # >= 2^31 were diverted to the int64 path at the top of this
    # function before any int32 work. The only non-power-of-two modulo
    # ((pivot - idx) mod count) reduces to one conditional add since
    # pivot - idx is in (-count, count).
    idx32 = idx.astype(np.int32)
    cnt = np.int32(count)
    for r in range(rounds):
        rh = hash32(seed + bytes([r]))
        pivot = np.int32(int.from_bytes(rh[:8], "little") % count)
        flip = pivot - idx32
        np.add(flip, cnt, out=flip, where=flip < 0)
        position = np.maximum(idx32, flip)
        if blocks_all is not None:
            flat = blocks_all[r].reshape(-1)
        else:
            # hashlib fallback (no C compiler on this host)
            flat = np.concatenate(
                [
                    np.frombuffer(
                        hash32(
                            seed
                            + bytes([r])
                            + int(b).to_bytes(4, "little")
                        ),
                        np.uint8,
                    )
                    for b in range(n_blocks)
                ]
            )
        # byte index: (position >> 8)*32 + ((position & 255) >> 3)
        byte = flat[
            ((position >> 8) << 5) + ((position & 255) >> 3)
        ]
        bit = (byte >> (position & 7).astype(np.uint8)) & 1
        idx32 = np.where(bit == 1, flip, idx32)
    return idx32.astype(np.int64)


def _compute_shuffling_int64(
    count: int, seed: bytes, blocks_all
) -> np.ndarray:
    """int64 swap-or-not rounds for registries >= 2^31 (spec limit is
    2^40). Same algorithm as the int32 fast path, per-round hashlib
    decision bytes (a registry this size is not a practical target)."""
    p = preset()
    idx = np.arange(count, dtype=np.int64)
    n_blocks = (count + 255) // 256
    for r in range(p.SHUFFLE_ROUND_COUNT):
        rh = hash32(seed + bytes([r]))
        pivot = np.int64(int.from_bytes(rh[:8], "little") % count)
        flip = (pivot - idx) % count
        position = np.maximum(idx, flip)
        if blocks_all is not None:
            flat = blocks_all[r].reshape(-1)
        else:
            flat = np.concatenate(
                [
                    np.frombuffer(
                        hash32(
                            seed + bytes([r]) + int(b).to_bytes(4, "little")
                        ),
                        np.uint8,
                    )
                    for b in range(n_blocks)
                ]
            )
        byte = flat[((position >> 8) << 5) + ((position & 255) >> 3)]
        bit = (byte >> (position & 7).astype(np.uint8)) & 1
        idx = np.where(bit == 1, flip, idx)
    return idx


# ---------------------------------------------------------------------------
# Committees / proposers
# ---------------------------------------------------------------------------


def compute_committee_count_per_slot(active_count: int) -> int:
    p = preset()
    return max(
        1,
        min(
            p.MAX_COMMITTEES_PER_SLOT,
            active_count // p.SLOTS_PER_EPOCH // p.TARGET_COMMITTEE_SIZE,
        ),
    )


def get_committee_count_per_slot(state, epoch: int) -> int:
    return compute_committee_count_per_slot(
        len(get_active_validator_indices(state, epoch))
    )


class EpochShuffling:
    """All committees of one epoch, computed in one shuffle pass.

    Reference analog: EpochShuffling (state-transition/src/util/
    epochShuffling.ts) cached per epoch in the EpochCache.
    """

    def __init__(self, state, epoch: int, _active=None, _seed=None):
        self.epoch = epoch
        active = (
            _active
            if _active is not None
            else np.asarray(
                get_active_validator_indices(state, epoch), np.int64
            )
        )
        self.active_indices = active
        seed = (
            _seed
            if _seed is not None
            else get_seed(state, epoch, DOMAIN_BEACON_ATTESTER)
        )
        if len(active):
            # spec compute_committee: position i holds
            # indices[compute_shuffled_index(i)] — the forward map
            self.shuffled = active[compute_shuffling(len(active), seed)]
        else:
            self.shuffled = active
        self.committees_per_slot = compute_committee_count_per_slot(
            len(active)
        )

    def committees_at_slot(self, slot: int) -> list[np.ndarray]:
        p = preset()
        n = len(self.shuffled)
        per_slot = self.committees_per_slot
        total = per_slot * p.SLOTS_PER_EPOCH
        slot_in_epoch = slot % p.SLOTS_PER_EPOCH
        out = []
        for i in range(per_slot):
            ci = slot_in_epoch * per_slot + i
            start = n * ci // total
            end = n * (ci + 1) // total
            out.append(self.shuffled[start:end])
        return out

    def committee(self, slot: int, index: int) -> np.ndarray:
        return self.committees_at_slot(slot)[index]


def get_beacon_committee(state, slot: int, index: int) -> np.ndarray:
    epoch = compute_epoch_at_slot(slot)
    return get_shuffling(state, epoch).committee(slot, index)


# Shufflings are deterministic in (seed, active index set); one bounded
# process-wide memo serves every block/state on every fork (reference:
# ShufflingCache, beacon-node/src/chain/shufflingCache.ts:56, fed from
# the EpochCache). VERDICT r1 item 6: carried across blocks instead of
# rebuilt per BlockCtx.
_SHUFFLINGS: dict[tuple, EpochShuffling] = {}
_SHUFFLINGS_MAX = 64


def get_shuffling(state, epoch: int) -> EpochShuffling:
    seed = get_seed(state, epoch, DOMAIN_BEACON_ATTESTER)
    active = np.asarray(get_active_validator_indices(state, epoch), np.int64)
    key = (epoch, seed, sha256(active.tobytes()).digest())
    hit = _SHUFFLINGS.get(key)
    if hit is not None:
        return hit
    sh = EpochShuffling(state, epoch, _active=active, _seed=seed)
    if len(_SHUFFLINGS) >= _SHUFFLINGS_MAX:
        _SHUFFLINGS.pop(next(iter(_SHUFFLINGS)))
    _SHUFFLINGS[key] = sh
    return sh


MAX_RANDOM_BYTE = 2**8 - 1
MAX_RANDOM_VALUE_ELECTRA = 2**16 - 1


def compute_proposer_index(
    state, indices, seed: bytes, electra: bool = False
) -> int:
    """Spec compute_proposer_index: rejection-sample by effective
    balance. Pre-electra draws 1 random byte per candidate; electra
    draws 2 (EIP-7251 raises max effective balance 64x)."""
    assert len(indices) > 0
    p = preset()
    max_eb = (
        p.MAX_EFFECTIVE_BALANCE_ELECTRA if electra else p.MAX_EFFECTIVE_BALANCE
    )
    max_rand = MAX_RANDOM_VALUE_ELECTRA if electra else MAX_RANDOM_BYTE
    total = len(indices)
    i = 0
    while True:
        candidate = indices[compute_shuffled_index(i % total, total, seed)]
        pos = i % (16 if electra else 32)
        source = hash32(seed + uint_to_bytes8(i // (16 if electra else 32)))
        if electra:
            rand = int.from_bytes(source[pos * 2 : pos * 2 + 2], "little")
        else:
            rand = source[pos]
        eb = state.validators[int(candidate)].effective_balance
        if eb * max_rand >= max_eb * rand:
            return int(candidate)
        i += 1


def get_beacon_proposer_index(state, electra: bool = False) -> int:
    epoch = get_current_epoch(state)
    seed = hash32(
        get_seed(state, epoch, DOMAIN_BEACON_PROPOSER)
        + uint_to_bytes8(state.slot)
    )
    indices = get_active_validator_indices(state, epoch)
    return compute_proposer_index(state, indices, seed, electra=electra)


# ---------------------------------------------------------------------------
# Sync committee selection (altair)
# ---------------------------------------------------------------------------


def get_next_sync_committee_indices(state, electra: bool = False) -> list[int]:
    """Spec get_next_sync_committee_indices: seeded rejection sampling
    over the active set at epoch+1."""
    p = preset()
    epoch = get_current_epoch(state) + 1
    active = get_active_validator_indices(state, epoch)
    count = len(active)
    seed = get_seed(state, epoch, DOMAIN_SYNC_COMMITTEE)
    max_eb = (
        p.MAX_EFFECTIVE_BALANCE_ELECTRA if electra else p.MAX_EFFECTIVE_BALANCE
    )
    max_rand = MAX_RANDOM_VALUE_ELECTRA if electra else MAX_RANDOM_BYTE
    out: list[int] = []
    i = 0
    while len(out) < p.SYNC_COMMITTEE_SIZE:
        shuffled = compute_shuffled_index(i % count, count, seed)
        candidate = active[shuffled]
        pos = i % (16 if electra else 32)
        source = hash32(seed + uint_to_bytes8(i // (16 if electra else 32)))
        if electra:
            rand = int.from_bytes(source[pos * 2 : pos * 2 + 2], "little")
        else:
            rand = source[pos]
        eb = state.validators[candidate].effective_balance
        if eb * max_rand >= max_eb * rand:
            out.append(candidate)
        i += 1
    return out


# ---------------------------------------------------------------------------
# Balances / churn
# ---------------------------------------------------------------------------


def increase_balance(state, index: int, delta: int) -> None:
    state.balances[index] += int(delta)


def decrease_balance(state, index: int, delta: int) -> None:
    state.balances[index] = max(0, state.balances[index] - int(delta))


def get_total_balance(state, indices) -> int:
    p = preset()
    total = sum(state.validators[int(i)].effective_balance for i in indices)
    return max(p.EFFECTIVE_BALANCE_INCREMENT, total)


def get_total_active_balance(state) -> int:
    return get_total_balance(
        state, get_active_validator_indices(state, get_current_epoch(state))
    )


def get_validator_churn_limit(cfg, state) -> int:
    active = len(get_active_validator_indices(state, get_current_epoch(state)))
    return max(
        cfg.MIN_PER_EPOCH_CHURN_LIMIT, active // cfg.CHURN_LIMIT_QUOTIENT
    )


def get_validator_activation_churn_limit(cfg, state) -> int:
    """Deneb EIP-7514 cap on the activation churn."""
    return min(
        cfg.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT,
        get_validator_churn_limit(cfg, state),
    )


# Electra (EIP-7251) balance-denominated churn
def get_balance_churn_limit(cfg, state) -> int:
    p = preset()
    churn = max(
        cfg.MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA,
        get_total_active_balance(state) // cfg.CHURN_LIMIT_QUOTIENT,
    )
    return churn - churn % p.EFFECTIVE_BALANCE_INCREMENT


def get_activation_exit_churn_limit(cfg, state) -> int:
    return min(
        cfg.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT,
        get_balance_churn_limit(cfg, state),
    )


def get_consolidation_churn_limit(cfg, state) -> int:
    return get_balance_churn_limit(cfg, state) - get_activation_exit_churn_limit(
        cfg, state
    )


# ---------------------------------------------------------------------------
# Exits / slashing mechanics
# ---------------------------------------------------------------------------


def initiate_validator_exit(cfg, state, index: int) -> None:
    """Pre-electra exit queue (count churn)."""
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_epochs = [
        w.exit_epoch
        for w in state.validators
        if w.exit_epoch != FAR_FUTURE_EPOCH
    ]
    exit_queue_epoch = max(
        exit_epochs
        + [compute_activation_exit_epoch(get_current_epoch(state))]
    )
    exit_queue_churn = sum(
        1 for w in state.validators if w.exit_epoch == exit_queue_epoch
    )
    if exit_queue_churn >= get_validator_churn_limit(cfg, state):
        exit_queue_epoch += 1
    v = mut(state.validators, index)
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = (
        exit_queue_epoch + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )


def compute_exit_epoch_and_update_churn(cfg, state, exit_balance: int) -> int:
    """Electra balance-churn exit scheduling (EIP-7251)."""
    earliest = max(
        state.earliest_exit_epoch,
        compute_activation_exit_epoch(get_current_epoch(state)),
    )
    per_epoch_churn = get_activation_exit_churn_limit(cfg, state)
    if state.earliest_exit_epoch < earliest:
        exit_balance_to_consume = per_epoch_churn
    else:
        exit_balance_to_consume = state.exit_balance_to_consume
    if exit_balance > exit_balance_to_consume:
        balance_to_process = exit_balance - exit_balance_to_consume
        additional_epochs = (balance_to_process - 1) // per_epoch_churn + 1
        earliest += additional_epochs
        exit_balance_to_consume += additional_epochs * per_epoch_churn
    state.exit_balance_to_consume = exit_balance_to_consume - exit_balance
    state.earliest_exit_epoch = earliest
    return earliest


def initiate_validator_exit_electra(cfg, state, index: int) -> None:
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_queue_epoch = compute_exit_epoch_and_update_churn(
        cfg, state, v.effective_balance
    )
    v = mut(state.validators, index)
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = (
        exit_queue_epoch + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )


def slash_validator(
    cfg, state, slashed_index: int, fork_seq: int, whistleblower_index=None
) -> None:
    """Spec slash_validator with per-fork quotients."""
    from ..params import ForkSeq

    p = preset()
    epoch = get_current_epoch(state)
    if fork_seq >= ForkSeq.electra:
        initiate_validator_exit_electra(cfg, state, slashed_index)
    else:
        initiate_validator_exit(cfg, state, slashed_index)
    v = mut(state.validators, slashed_index)
    v.slashed = True
    v.withdrawable_epoch = max(
        v.withdrawable_epoch, epoch + p.EPOCHS_PER_SLASHINGS_VECTOR
    )
    state.slashings[epoch % p.EPOCHS_PER_SLASHINGS_VECTOR] += (
        v.effective_balance
    )
    if fork_seq >= ForkSeq.electra:
        quotient = p.MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA
    elif fork_seq >= ForkSeq.bellatrix:
        quotient = p.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX
    elif fork_seq >= ForkSeq.altair:
        quotient = p.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    else:
        quotient = p.MIN_SLASHING_PENALTY_QUOTIENT
    decrease_balance(state, slashed_index, v.effective_balance // quotient)

    proposer_index = get_beacon_proposer_index(
        state, electra=fork_seq >= ForkSeq.electra
    )
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    if fork_seq >= ForkSeq.electra:
        whistleblower_reward = (
            v.effective_balance // p.WHISTLEBLOWER_REWARD_QUOTIENT_ELECTRA
        )
    else:
        whistleblower_reward = (
            v.effective_balance // p.WHISTLEBLOWER_REWARD_QUOTIENT
        )
    if fork_seq >= ForkSeq.altair:
        proposer_reward = (
            whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
        )
    else:
        proposer_reward = whistleblower_reward // p.PROPOSER_REWARD_QUOTIENT
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(
        state, whistleblower_index, whistleblower_reward - proposer_reward
    )


# ---------------------------------------------------------------------------
# Altair participation flags / weights
# ---------------------------------------------------------------------------

TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2

TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64

PARTICIPATION_FLAG_WEIGHTS = [
    TIMELY_SOURCE_WEIGHT,
    TIMELY_TARGET_WEIGHT,
    TIMELY_HEAD_WEIGHT,
]


def add_flag(flags: int, flag_index: int) -> int:
    return flags | (1 << flag_index)


def has_flag(flags: int, flag_index: int) -> bool:
    return bool(flags & (1 << flag_index))

"""Extract every signature set of a signed block for batch verification.

Reference analog: getBlockSignatureSets
(state-transition/src/signatureSets/index.ts:26) and its per-operation
extractors (proposer, randao, attestations, slashings, exits, sync
committee, blsToExecutionChange). Block import runs the state
transition with signature checks off and ships these sets to the TPU
verifier pool instead (chain/blocks/verifyBlocksSignatures.ts:18-77).
"""

from __future__ import annotations

from ..bls.api import SignatureSet
from ..config.beacon_config import compute_domain
from ..crypto.bls.signature import aggregate_pubkeys
from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_BLS_TO_EXECUTION_CHANGE,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_VOLUNTARY_EXIT,
    ForkSeq,
    preset,
)
from ..ssz import uint64 as ssz_uint64
from .block import BlockCtx, compute_signing_root, get_domain
from .util import compute_epoch_at_slot, get_block_root_at_slot, get_current_epoch


def proposer_signature_set(cfg, view, signed_block, types) -> SignatureSet:
    state = view.state
    block = signed_block.message
    proposer = state.validators[block.proposer_index]
    domain = get_domain(cfg, state, DOMAIN_BEACON_PROPOSER)
    block_t = types.by_fork[view.fork].BeaconBlock
    root = compute_signing_root(block_t, block, domain)
    return SignatureSet(
        bytes(proposer.pubkey), root, bytes(signed_block.signature)
    )


def randao_signature_set(cfg, view, block, types) -> SignatureSet:
    state = view.state
    proposer = state.validators[block.proposer_index]
    epoch = get_current_epoch(state)
    domain = get_domain(cfg, state, DOMAIN_RANDAO)
    root = compute_signing_root(ssz_uint64, epoch, domain)
    return SignatureSet(
        bytes(proposer.pubkey), root, bytes(block.body.randao_reveal)
    )


def attestation_signature_sets(cfg, view, block, types) -> list[SignatureSet]:
    from .block import get_attesting_indices

    state = view.state
    ctx = BlockCtx(cfg, state, types, view.fork_seq, False)
    out = []
    for att in block.body.attestations:
        indices = get_attesting_indices(ctx, att)
        pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
        domain = get_domain(
            cfg, state, DOMAIN_BEACON_ATTESTER, att.data.target.epoch
        )
        root = compute_signing_root(types.AttestationData, att.data, domain)
        out.append(
            SignatureSet(
                aggregate_pubkeys(pubkeys), root, bytes(att.signature)
            )
        )
    return out


def proposer_slashing_signature_sets(cfg, view, block, types) -> list[SignatureSet]:
    state = view.state
    out = []
    for ps in block.body.proposer_slashings:
        proposer = state.validators[
            ps.signed_header_1.message.proposer_index
        ]
        for signed in (ps.signed_header_1, ps.signed_header_2):
            domain = get_domain(
                cfg,
                state,
                DOMAIN_BEACON_PROPOSER,
                compute_epoch_at_slot(signed.message.slot),
            )
            root = compute_signing_root(
                types.BeaconBlockHeader, signed.message, domain
            )
            out.append(
                SignatureSet(
                    bytes(proposer.pubkey), root, bytes(signed.signature)
                )
            )
    return out


def attester_slashing_signature_sets(cfg, view, block, types) -> list[SignatureSet]:
    state = view.state
    out = []
    for s in block.body.attester_slashings:
        for indexed in (s.attestation_1, s.attestation_2):
            pubkeys = [
                bytes(state.validators[int(i)].pubkey)
                for i in indexed.attesting_indices
            ]
            domain = get_domain(
                cfg,
                state,
                DOMAIN_BEACON_ATTESTER,
                indexed.data.target.epoch,
            )
            root = compute_signing_root(
                types.AttestationData, indexed.data, domain
            )
            out.append(
                SignatureSet(
                    aggregate_pubkeys(pubkeys), root, bytes(indexed.signature)
                )
            )
    return out


def voluntary_exit_signature_sets(cfg, view, block, types) -> list[SignatureSet]:
    state = view.state
    out = []
    for signed in block.body.voluntary_exits:
        v = state.validators[signed.message.validator_index]
        if view.fork_seq >= ForkSeq.deneb:  # EIP-7044
            domain = compute_domain(
                DOMAIN_VOLUNTARY_EXIT,
                cfg.CAPELLA_FORK_VERSION,
                state.genesis_validators_root,
            )
        else:
            domain = get_domain(
                cfg, state, DOMAIN_VOLUNTARY_EXIT, signed.message.epoch
            )
        root = compute_signing_root(
            types.VoluntaryExit, signed.message, domain
        )
        out.append(
            SignatureSet(bytes(v.pubkey), root, bytes(signed.signature))
        )
    return out


def sync_aggregate_signature_set(cfg, view, block, types) -> SignatureSet | None:
    from ..config.beacon_config import compute_signing_root_from_roots

    state = view.state
    sa = block.body.sync_aggregate
    bits = list(sa.sync_committee_bits)
    participants = [
        bytes(pk)
        for pk, b in zip(state.current_sync_committee.pubkeys, bits)
        if b
    ]
    if not participants:
        return None
    previous_slot = max(block.slot, 1) - 1
    domain = get_domain(
        cfg, state, DOMAIN_SYNC_COMMITTEE, compute_epoch_at_slot(previous_slot)
    )
    root = compute_signing_root_from_roots(
        get_block_root_at_slot(state, previous_slot), domain
    )
    return SignatureSet(
        aggregate_pubkeys(participants),
        root,
        bytes(sa.sync_committee_signature),
    )


def bls_to_execution_change_signature_sets(
    cfg, view, block, types
) -> list[SignatureSet]:
    state = view.state
    out = []
    for signed in block.body.bls_to_execution_changes:
        domain = compute_domain(
            DOMAIN_BLS_TO_EXECUTION_CHANGE,
            cfg.GENESIS_FORK_VERSION,
            state.genesis_validators_root,
        )
        root = compute_signing_root(
            types.BLSToExecutionChange, signed.message, domain
        )
        out.append(
            SignatureSet(
                bytes(signed.message.from_bls_pubkey),
                root,
                bytes(signed.signature),
            )
        )
    return out


def get_block_signature_sets(
    cfg,
    view,
    signed_block,
    types,
    include_proposer: bool = True,
) -> list[SignatureSet]:
    """All signature sets of one signed block, in the reference's order
    (signatureSets/index.ts:26-60). The state must already be advanced
    to the block's slot."""
    block = signed_block.message
    sets: list[SignatureSet] = []
    if include_proposer:
        sets.append(proposer_signature_set(cfg, view, signed_block, types))
    sets.append(randao_signature_set(cfg, view, block, types))
    sets.extend(proposer_slashing_signature_sets(cfg, view, block, types))
    sets.extend(attester_slashing_signature_sets(cfg, view, block, types))
    sets.extend(attestation_signature_sets(cfg, view, block, types))
    sets.extend(voluntary_exit_signature_sets(cfg, view, block, types))
    if view.fork_seq >= ForkSeq.altair:
        sync_set = sync_aggregate_signature_set(cfg, view, block, types)
        if sync_set is not None:
            sets.append(sync_set)
    if view.fork_seq >= ForkSeq.capella:
        sets.extend(
            bls_to_execution_change_signature_sets(cfg, view, block, types)
        )
    return sets

"""Slot processing, fork upgrades, and the state_transition entry.

Reference analog: packages/state-transition/src/stateTransition.ts:64
(stateTransition/processSlots) and src/slot/upgradeStateTo*.ts.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..ssz.cached import SszVec
from ..config.beacon_config import compute_domain
from ..params import (
    DOMAIN_BEACON_PROPOSER,
    FAR_FUTURE_EPOCH,
    FORK_ORDER,
    GENESIS_SLOT,
    ForkSeq,
    preset,
)
from . import block as blockproc
from . import epoch as epochproc
from . import util
from .block import (
    G2_POINT_AT_INFINITY,
    UNSET_DEPOSIT_REQUESTS_START_INDEX,
    BlockProcessError,
    _req,
    compute_signing_root,
    get_domain,
    has_compounding_withdrawal_credential,
)


@dataclass
class BeaconStateView:
    """A beacon state value + which fork's container type it is.

    Reference analog: CachedBeaconState<F> — the fork is part of the
    static type there (state-transition/src/cache/stateCache.ts);
    here it's carried alongside the plain SSZ value.
    """

    state: object
    fork: str  # ForkName

    @property
    def fork_seq(self) -> int:
        return int(ForkSeq[self.fork])

    def state_type(self, types):
        return types.by_fork[self.fork].BeaconState

    def hash_tree_root(self, types) -> bytes:
        return self.state_type(types).hash_tree_root(self.state)


def fork_at_epoch(cfg, epoch: int) -> str:
    """Highest fork active at epoch (config fork schedule)."""
    name = "phase0"
    for fork, ep in (
        ("altair", cfg.ALTAIR_FORK_EPOCH),
        ("bellatrix", cfg.BELLATRIX_FORK_EPOCH),
        ("capella", cfg.CAPELLA_FORK_EPOCH),
        ("deneb", cfg.DENEB_FORK_EPOCH),
        ("electra", cfg.ELECTRA_FORK_EPOCH),
    ):
        if epoch >= ep:
            name = fork
    return name


def process_slot(cfg, view: BeaconStateView, types) -> None:
    p = preset()
    state = view.state
    prev_state_root = view.hash_tree_root(types)
    state.state_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = (
        prev_state_root
    )
    if bytes(state.latest_block_header.state_root) == b"\x00" * 32:
        state.latest_block_header.state_root = prev_state_root
    prev_block_root = types.BeaconBlockHeader.hash_tree_root(
        state.latest_block_header
    )
    state.block_roots[state.slot % p.SLOTS_PER_HISTORICAL_ROOT] = (
        prev_block_root
    )


def process_slots(cfg, view: BeaconStateView, slot: int, types) -> None:
    p = preset()
    state = view.state
    if state.slot > slot:
        raise BlockProcessError(
            f"cannot rewind state from {state.slot} to {slot}"
        )
    while state.slot < slot:
        process_slot(cfg, view, types)
        if (state.slot + 1) % p.SLOTS_PER_EPOCH == 0:
            epochproc.process_epoch(cfg, state, types, view.fork_seq)
        state.slot += 1
        if state.slot % p.SLOTS_PER_EPOCH == 0:
            epoch = state.slot // p.SLOTS_PER_EPOCH
            _maybe_upgrade(cfg, view, epoch, types)
            state = view.state  # upgrades replace the state object


def _maybe_upgrade(cfg, view: BeaconStateView, epoch: int, types) -> None:
    upgrades = {
        "altair": (cfg.ALTAIR_FORK_EPOCH, upgrade_to_altair),
        "bellatrix": (cfg.BELLATRIX_FORK_EPOCH, upgrade_to_bellatrix),
        "capella": (cfg.CAPELLA_FORK_EPOCH, upgrade_to_capella),
        "deneb": (cfg.DENEB_FORK_EPOCH, upgrade_to_deneb),
        "electra": (cfg.ELECTRA_FORK_EPOCH, upgrade_to_electra),
    }
    for fork, (fork_epoch, fn) in upgrades.items():
        if epoch == fork_epoch and FORK_ORDER.index(fork) == view.fork_seq + 1:
            fn(cfg, view, types)


# ---------------------------------------------------------------------------
# Fork upgrades
# ---------------------------------------------------------------------------


def _copy_fields(old_state, new_state) -> None:
    for name in type(new_state)._type.field_names:
        if name in type(old_state)._type.field_names:
            setattr(new_state, name, getattr(old_state, name))


def _bump_fork(cfg, state, new_state, version: bytes, types) -> None:
    f = types.Fork.default()
    f.previous_version = bytes(state.fork.current_version)
    f.current_version = version
    f.epoch = util.get_current_epoch(state)
    new_state.fork = f


def upgrade_to_altair(cfg, view: BeaconStateView, types) -> None:
    """Reference: state-transition/src/slot/upgradeStateToAltair.ts."""
    from ..crypto.bls.signature import aggregate_pubkeys

    pre = view.state
    n = len(pre.validators)
    post = types.altair.BeaconState.default()
    _copy_fields(pre, post)
    _bump_fork(cfg, pre, post, cfg.ALTAIR_FORK_VERSION, types)
    post.previous_epoch_participation = SszVec([0] * n)
    post.current_epoch_participation = SszVec([0] * n)
    post.inactivity_scores = SszVec([0] * n)
    view.state = post
    view.fork = "altair"

    # translate_participation over pre.previous_epoch_attestations.
    # Spec translate_participation asserts is_matching_source inside
    # get_attestation_participation_flag_indices; a failure here means
    # the pre-state held an attestation with a non-matching source,
    # which is itself a bug — propagate rather than silently dropping
    # participation flags (would change post-upgrade rewards).
    ctx = blockproc.BlockCtx(cfg, post, types, ForkSeq.altair, False)
    for att in pre.previous_epoch_attestations:
        flags = blockproc.get_attestation_participation_flag_indices(
            ctx, att.data, att.inclusion_delay
        )
        shuffling = ctx.shuffling(att.data.target.epoch)
        committee = shuffling.committee(att.data.slot, att.data.index)
        bits = list(att.aggregation_bits)
        for i, v in enumerate(committee):
            if bits[i]:
                for flag in flags:
                    post.previous_epoch_participation[int(v)] = util.add_flag(
                        post.previous_epoch_participation[int(v)], flag
                    )

    indices = util.get_next_sync_committee_indices(post)
    pubkeys = [bytes(post.validators[i].pubkey) for i in indices]
    sc = types.SyncCommittee.default()
    sc.pubkeys = pubkeys
    sc.aggregate_pubkey = aggregate_pubkeys(pubkeys)
    post.current_sync_committee = sc
    indices = util.get_next_sync_committee_indices(post)
    pubkeys = [bytes(post.validators[i].pubkey) for i in indices]
    sc2 = types.SyncCommittee.default()
    sc2.pubkeys = pubkeys
    sc2.aggregate_pubkey = aggregate_pubkeys(pubkeys)
    post.next_sync_committee = sc2


def upgrade_to_bellatrix(cfg, view: BeaconStateView, types) -> None:
    pre = view.state
    post = types.bellatrix.BeaconState.default()
    _copy_fields(pre, post)
    _bump_fork(cfg, pre, post, cfg.BELLATRIX_FORK_VERSION, types)
    post.latest_execution_payload_header = (
        types.bellatrix.ExecutionPayloadHeader.default()
    )
    view.state = post
    view.fork = "bellatrix"


def upgrade_to_capella(cfg, view: BeaconStateView, types) -> None:
    pre = view.state
    post = types.capella.BeaconState.default()
    _copy_fields(pre, post)
    _bump_fork(cfg, pre, post, cfg.CAPELLA_FORK_VERSION, types)
    old = pre.latest_execution_payload_header
    hdr = types.capella.ExecutionPayloadHeader.default()
    for name, _ in types.bellatrix.ExecutionPayloadHeader.fields:
        setattr(hdr, name, getattr(old, name))
    post.latest_execution_payload_header = hdr
    post.next_withdrawal_index = 0
    post.next_withdrawal_validator_index = 0
    post.historical_summaries = SszVec()
    view.state = post
    view.fork = "capella"


def upgrade_to_deneb(cfg, view: BeaconStateView, types) -> None:
    pre = view.state
    post = types.deneb.BeaconState.default()
    _copy_fields(pre, post)
    _bump_fork(cfg, pre, post, cfg.DENEB_FORK_VERSION, types)
    old = pre.latest_execution_payload_header
    hdr = types.deneb.ExecutionPayloadHeader.default()
    for name, _ in types.capella.ExecutionPayloadHeader.fields:
        setattr(hdr, name, getattr(old, name))
    hdr.blob_gas_used = 0
    hdr.excess_blob_gas = 0
    post.latest_execution_payload_header = hdr
    view.state = post
    view.fork = "deneb"


def upgrade_to_electra(cfg, view: BeaconStateView, types) -> None:
    pre = view.state
    post = types.electra.BeaconState.default()
    _copy_fields(pre, post)
    _bump_fork(cfg, pre, post, cfg.ELECTRA_FORK_VERSION, types)
    cur = util.get_current_epoch(pre)
    exit_epochs = [
        v.exit_epoch
        for v in post.validators
        if v.exit_epoch != FAR_FUTURE_EPOCH
    ]
    post.earliest_exit_epoch = max(exit_epochs + [cur]) + 1
    post.deposit_requests_start_index = UNSET_DEPOSIT_REQUESTS_START_INDEX
    post.deposit_balance_to_consume = 0
    post.exit_balance_to_consume = util.get_activation_exit_churn_limit(
        cfg, post
    )
    post.consolidation_balance_to_consume = util.get_consolidation_churn_limit(
        cfg, post
    )
    post.earliest_consolidation_epoch = util.compute_activation_exit_epoch(
        cur
    )
    post.pending_deposits = SszVec()
    post.pending_partial_withdrawals = SszVec()
    post.pending_consolidations = SszVec()
    view.state = post
    view.fork = "electra"

    pre_activation = sorted(
        (
            i
            for i, v in enumerate(post.validators)
            if v.activation_epoch == FAR_FUTURE_EPOCH
        ),
        key=lambda i: (
            post.validators[i].activation_eligibility_epoch,
            i,
        ),
    )
    for i in pre_activation:
        _queue_entire_balance_and_reset_validator(post, i, types)
    for i, v in enumerate(post.validators):
        if has_compounding_withdrawal_credential(
            bytes(v.withdrawal_credentials)
        ):
            _queue_excess_active_balance(post, i, types)


def _queue_entire_balance_and_reset_validator(state, index: int, types) -> None:
    from .util import mut

    v = mut(state.validators, index)
    balance = state.balances[index]
    state.balances[index] = 0
    v.effective_balance = 0
    v.activation_eligibility_epoch = FAR_FUTURE_EPOCH
    pd = types.PendingDeposit.default()
    pd.pubkey = bytes(v.pubkey)
    pd.withdrawal_credentials = bytes(v.withdrawal_credentials)
    pd.amount = balance
    pd.signature = G2_POINT_AT_INFINITY
    pd.slot = GENESIS_SLOT
    state.pending_deposits.append(pd)


def _queue_excess_active_balance(state, index: int, types) -> None:
    p = preset()
    balance = state.balances[index]
    if balance > p.MIN_ACTIVATION_BALANCE:
        excess = balance - p.MIN_ACTIVATION_BALANCE
        state.balances[index] = p.MIN_ACTIVATION_BALANCE
        v = state.validators[index]
        pd = types.PendingDeposit.default()
        pd.pubkey = bytes(v.pubkey)
        pd.withdrawal_credentials = bytes(v.withdrawal_credentials)
        pd.amount = excess
        pd.signature = G2_POINT_AT_INFINITY
        pd.slot = GENESIS_SLOT
        state.pending_deposits.append(pd)


# ---------------------------------------------------------------------------
# Full transition
# ---------------------------------------------------------------------------


def verify_block_signature(cfg, view: BeaconStateView, signed_block, types) -> bool:
    from ..crypto.bls.signature import verify as bls_verify

    state = view.state
    block = signed_block.message
    proposer = state.validators[block.proposer_index]
    domain = get_domain(cfg, state, DOMAIN_BEACON_PROPOSER)
    block_t = types.by_fork[view.fork].BeaconBlock
    root = compute_signing_root(block_t, block, domain)
    return bls_verify(
        bytes(proposer.pubkey), root, bytes(signed_block.signature)
    )


def state_transition(
    cfg,
    view: BeaconStateView,
    signed_block,
    types,
    verify_state_root: bool = True,
    verify_proposer: bool = True,
    verify_signatures: bool = True,
    execution_engine=None,
) -> BeaconStateView:
    """Spec state_transition. Mutates and returns `view`.

    Production block import calls this with all verify flags False and
    batches the extracted signature sets through the TPU verifier
    instead (reference: verifyBlocksStateTransitionOnly +
    verifyBlocksSignatures in parallel, chain/blocks/verifyBlock.ts).
    """
    block = signed_block.message
    process_slots(cfg, view, block.slot, types)
    if verify_proposer:
        _req(
            verify_block_signature(cfg, view, signed_block, types),
            "invalid block signature",
        )
    blockproc.process_block(
        cfg,
        view.state,
        block,
        types,
        view.fork_seq,
        verify_signatures=verify_signatures,
        execution_engine=execution_engine,
    )
    if verify_state_root:
        _req(
            bytes(block.state_root) == view.hash_tree_root(types),
            "state root mismatch",
        )
    return view

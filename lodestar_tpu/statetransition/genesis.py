"""Genesis state construction: spec eth1-deposit genesis + interop.

Reference analog: GenesisBuilder (beacon-node/src/chain/genesis/
genesis.ts:40) for the deposit path, and the interop/dev genesis used
by `lodestar dev` (cli/src/cmds/dev/, beacon-node interop state
utilities). Interop keys follow the EF interop spec: sk_i =
int(sha256(uint256_le(i))) mod r.
"""

from __future__ import annotations

from hashlib import sha256

from ..ssz.cached import SszVec
from ..crypto.bls.fields import R as CURVE_ORDER
from ..crypto.bls.signature import sk_to_pk
from ..params import (
    BLS_WITHDRAWAL_PREFIX,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    ForkSeq,
    preset,
)
from .slot import BeaconStateView, fork_at_epoch
from .util import get_next_sync_committee_indices


def interop_secret_key(index: int) -> int:
    h = sha256(index.to_bytes(32, "little")).digest()
    return int.from_bytes(h, "little") % CURVE_ORDER


def interop_pubkeys(n: int) -> list[bytes]:
    return [sk_to_pk(interop_secret_key(i)) for i in range(n)]


def bls_withdrawal_credentials(pubkey: bytes) -> bytes:
    return BLS_WITHDRAWAL_PREFIX + sha256(pubkey).digest()[1:]


class GenesisBuilder:
    """Build genesis from real eth1 deposits.

    Reference analog: GenesisBuilder (chain/genesis/genesis.ts:40) +
    spec initialize_beacon_state_from_eth1 / is_valid_genesis_state:
    deposits stream in (from the eth1 tracker), each applied through
    the spec deposit path with an incremental deposit root; genesis
    triggers once MIN_GENESIS_ACTIVE_VALIDATOR_COUNT active validators
    exist at MIN_GENESIS_TIME.
    """

    def __init__(self, cfg, types):
        from ..eth1.deposit_tree import DepositTree

        self.cfg = cfg
        self.types = types
        p = preset()
        fork = fork_at_epoch(cfg, GENESIS_EPOCH)
        if fork != "phase0":
            # post-phase0 genesis needs participation/sync-committee/
            # payload-header seeding this builder doesn't do (mainnet
            # genesis was phase0; later-fork genesis uses
            # create_interop_genesis_state for dev nets)
            raise NotImplementedError(
                f"eth1 genesis builder supports phase0 genesis only "
                f"(config puts genesis at {fork})"
            )
        self.fork = fork
        self.state = types.by_fork[fork].BeaconState.default()
        self.state.fork = _genesis_fork(cfg, types, fork)
        header = types.BeaconBlockHeader.default()
        ns = types.by_fork[fork]
        header.body_root = ns.BeaconBlockBody.hash_tree_root(
            ns.BeaconBlockBody.default()
        )
        self.state.latest_block_header = header
        self.tree = DepositTree()
        self.deposits_applied = 0

    def apply_eth1_block(self, block_hash: bytes, timestamp: int) -> None:
        """Candidate genesis eth1 block (genesis.ts onBlock)."""
        p = preset()
        self.state.eth1_data.block_hash = bytes(block_hash)
        self.state.genesis_time = (
            int(timestamp) + self.cfg.GENESIS_DELAY
        )
        self.state.randao_mixes = SszVec(
            [bytes(block_hash)] * p.EPOCHS_PER_HISTORICAL_VECTOR
        )

    def apply_deposits(self, deposit_datas) -> None:
        """Spec: each deposit is processed against the tree root of the
        deposits applied SO FAR (incremental eth1_data during genesis)."""
        from .block import BlockCtx, process_deposit

        for dd in deposit_datas:
            self.tree.push(
                self.types.DepositData.hash_tree_root(dd)
            )
            count = len(self.tree)
            self.state.eth1_data.deposit_root = self.tree.root
            self.state.eth1_data.deposit_count = count
            dep = self.types.Deposit.default()
            dep.data = dd
            dep.proof = self.tree.branch(count - 1, count)
            ctx = BlockCtx(
                self.cfg, self.state, self.types,
                int(ForkSeq[self.fork]), True,
            )
            process_deposit(ctx, dep)
            self.deposits_applied += 1
        self._activate_genesis_validators()

    def _activate_genesis_validators(self) -> None:
        from .util import mut

        p = preset()
        for i, v in enumerate(self.state.validators):
            # spec initialize_beacon_state_from_eth1 recomputes the
            # effective balance from the FINAL balance (split deposits
            # top up plain balance only) before the activation check
            balance = int(self.state.balances[i])
            effective = min(
                balance - balance % p.EFFECTIVE_BALANCE_INCREMENT,
                p.MAX_EFFECTIVE_BALANCE,
            )
            if int(v.effective_balance) != effective:
                mut(self.state.validators, i).effective_balance = (
                    effective
                )
                v = self.state.validators[i]
            if (
                v.activation_epoch == FAR_FUTURE_EPOCH
                and v.effective_balance == p.MAX_EFFECTIVE_BALANCE
            ):
                w = mut(self.state.validators, i)
                w.activation_eligibility_epoch = GENESIS_EPOCH
                w.activation_epoch = GENESIS_EPOCH

    def is_valid_genesis(self) -> bool:
        """Spec is_valid_genesis_state."""
        if self.state.genesis_time < self.cfg.MIN_GENESIS_TIME:
            return False
        active = sum(
            1
            for v in self.state.validators
            if v.activation_epoch == GENESIS_EPOCH
        )
        return active >= self.cfg.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT

    def finalize(self):
        """Seal genesis_validators_root; returns the BeaconStateView."""
        p = preset()
        from ..ssz import ListType

        validators_t = ListType(
            self.types.Validator, p.VALIDATOR_REGISTRY_LIMIT
        )
        self.state.genesis_validators_root = validators_t.hash_tree_root(
            list(self.state.validators)
        )
        return BeaconStateView(state=self.state, fork=self.fork)


def _genesis_fork(cfg, types, fork: str):
    f = types.Fork.default()
    versions = {
        "phase0": (cfg.GENESIS_FORK_VERSION, cfg.GENESIS_FORK_VERSION),
        "altair": (cfg.GENESIS_FORK_VERSION, cfg.ALTAIR_FORK_VERSION),
        "bellatrix": (cfg.ALTAIR_FORK_VERSION, cfg.BELLATRIX_FORK_VERSION),
        "capella": (cfg.BELLATRIX_FORK_VERSION, cfg.CAPELLA_FORK_VERSION),
        "deneb": (cfg.CAPELLA_FORK_VERSION, cfg.DENEB_FORK_VERSION),
        "electra": (cfg.DENEB_FORK_VERSION, cfg.ELECTRA_FORK_VERSION),
    }
    f.previous_version, f.current_version = versions[fork]
    f.epoch = GENESIS_EPOCH
    return f


def create_interop_genesis_state(
    cfg,
    types,
    n_validators: int,
    genesis_time: int = 0,
    eth1_block_hash: bytes = b"\x42" * 32,
    fork: str | None = None,
    pubkeys: list[bytes] | None = None,
):
    """Deterministic pre-activated genesis state at the configured
    genesis fork (or an explicit one), for dev chains and tests."""
    p = preset()
    if fork is None:
        fork = fork_at_epoch(cfg, GENESIS_EPOCH)
    fork_seq = int(ForkSeq[fork])
    ns = types.by_fork[fork]
    state = ns.BeaconState.default()

    state.genesis_time = genesis_time
    state.fork = _genesis_fork(cfg, types, fork)

    if pubkeys is None:
        pubkeys = interop_pubkeys(n_validators)
    for pk in pubkeys:
        v = types.Validator.default()
        v.pubkey = pk
        v.withdrawal_credentials = bls_withdrawal_credentials(pk)
        v.effective_balance = p.MAX_EFFECTIVE_BALANCE
        v.slashed = False
        v.activation_eligibility_epoch = GENESIS_EPOCH
        v.activation_epoch = GENESIS_EPOCH
        v.exit_epoch = FAR_FUTURE_EPOCH
        v.withdrawable_epoch = FAR_FUTURE_EPOCH
        state.validators.append(v)
        state.balances.append(p.MAX_EFFECTIVE_BALANCE)

    state.randao_mixes = SszVec([eth1_block_hash] * p.EPOCHS_PER_HISTORICAL_VECTOR)
    eth1 = types.Eth1Data.default()
    eth1.block_hash = eth1_block_hash
    eth1.deposit_count = len(pubkeys)
    state.eth1_data = eth1
    state.eth1_deposit_index = len(pubkeys)

    header = types.BeaconBlockHeader.default()
    header.body_root = ns.BeaconBlockBody.hash_tree_root(
        ns.BeaconBlockBody.default()
    )
    state.latest_block_header = header

    from ..ssz import ListType

    validators_t = ListType(types.Validator, p.VALIDATOR_REGISTRY_LIMIT)
    state.genesis_validators_root = validators_t.hash_tree_root(
        list(state.validators)
    )

    if fork_seq >= ForkSeq.altair:
        n = len(pubkeys)
        state.previous_epoch_participation = SszVec([0] * n)
        state.current_epoch_participation = SszVec([0] * n)
        state.inactivity_scores = SszVec([0] * n)
        _set_genesis_sync_committees(state, types, fork_seq)
    if fork_seq >= ForkSeq.bellatrix:
        # latest_execution_payload_header: pretend-merged genesis with
        # the eth1 block as terminal block (dev-chain convention)
        hdr = ns.ExecutionPayloadHeader.default()
        hdr.block_hash = eth1_block_hash
        state.latest_execution_payload_header = hdr
    if fork_seq >= ForkSeq.electra:
        from .block import UNSET_DEPOSIT_REQUESTS_START_INDEX

        state.deposit_requests_start_index = (
            UNSET_DEPOSIT_REQUESTS_START_INDEX
        )
        state.earliest_exit_epoch = GENESIS_EPOCH + 1
        from .util import (
            compute_activation_exit_epoch,
            get_activation_exit_churn_limit,
            get_consolidation_churn_limit,
        )

        state.exit_balance_to_consume = get_activation_exit_churn_limit(
            cfg, state
        )
        state.consolidation_balance_to_consume = (
            get_consolidation_churn_limit(cfg, state)
        )
        state.earliest_consolidation_epoch = compute_activation_exit_epoch(
            GENESIS_EPOCH
        )
    return BeaconStateView(state=state, fork=fork)


def _set_genesis_sync_committees(state, types, fork_seq) -> None:
    from ..crypto.bls.signature import aggregate_pubkeys

    indices = get_next_sync_committee_indices(
        state, electra=fork_seq >= ForkSeq.electra
    )
    pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
    sc = types.SyncCommittee.default()
    sc.pubkeys = pubkeys
    sc.aggregate_pubkey = aggregate_pubkeys(pubkeys)
    state.current_sync_committee = sc
    sc2 = types.SyncCommittee.default()
    sc2.pubkeys = list(pubkeys)
    sc2.aggregate_pubkey = aggregate_pubkeys(pubkeys)
    state.next_sync_committee = sc2

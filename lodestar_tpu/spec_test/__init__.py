"""Consensus-spec-tests harness.

Reference analog: packages/spec-test-util (describeDirectorySpecTest,
src/single.ts:94) + beacon-node/test/spec/presets/* — a generic runner
over the official ethereum/consensus-spec-tests directory layout:

  <root>/tests/<preset>/<fork>/<runner>/<handler>/<suite>/<case>/
      pre.ssz_snappy, post.ssz_snappy, blocks_0.ssz_snappy,
      meta.yaml, ...

Vectors are an external download (zero-egress environments run the
differential/adversarial suites instead — tests/test_bls_native.py,
tests/test_ops_*); point LODESTAR_SPEC_TESTS at an unpacked checkout
and tests/test_spec_vectors.py runs everything this runner understands.
"""

from .runner import (
    SpecCase,
    discover_cases,
    run_epoch_processing_case,
    run_finality_case,
    run_operations_case,
    run_sanity_blocks_case,
    run_sanity_slots_case,
)

__all__ = [
    "SpecCase",
    "discover_cases",
    "run_epoch_processing_case",
    "run_operations_case",
    "run_sanity_blocks_case",
    "run_sanity_slots_case",
    "run_finality_case",
]

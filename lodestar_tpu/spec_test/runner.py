"""Directory runner over the consensus-spec-tests layout.

Reference analog: spec-test-util/src/single.ts:94
(describeDirectorySpecTest) and the per-suite bindings in
beacon-node/test/spec/presets/{operations,epoch_processing,sanity,
finality}.ts. A case directory's *.ssz_snappy files decode with this
repo's own snappy + SSZ; expected-failure cases have no post state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from ..statetransition import BeaconStateView
from ..statetransition import epoch as E
from ..statetransition import util
from ..statetransition.block import BlockCtx, BlockProcessError
from ..statetransition.slot import process_slots, state_transition
from ..params import ForkSeq
from ..utils import snappy

FORKS = ("phase0", "altair", "bellatrix", "capella", "deneb", "electra")


@dataclass
class SpecCase:
    preset: str
    fork: str
    runner: str
    handler: str
    suite: str
    name: str
    path: Path

    def read_ssz(self, fname: str) -> bytes | None:
        f = self.path / f"{fname}.ssz_snappy"
        if not f.exists():
            return None
        return snappy.uncompress(f.read_bytes())

    def read_yaml(self, fname: str):
        f = self.path / f"{fname}.yaml"
        if not f.exists():
            return None
        import yaml

        return yaml.safe_load(f.read_text())


def discover_cases(root: Path, preset: str) -> list[SpecCase]:
    """tests/<preset>/<fork>/<runner>/<handler>/<suite>/<case>/"""
    out = []
    base = Path(root) / "tests" / preset
    if not base.is_dir():
        return out
    for fork_dir in sorted(base.iterdir()):
        if fork_dir.name not in FORKS:
            continue
        for runner_dir in sorted(p for p in fork_dir.iterdir() if p.is_dir()):
            for handler_dir in sorted(
                p for p in runner_dir.iterdir() if p.is_dir()
            ):
                for suite_dir in sorted(
                    p for p in handler_dir.iterdir() if p.is_dir()
                ):
                    for case_dir in sorted(
                        p for p in suite_dir.iterdir() if p.is_dir()
                    ):
                        out.append(
                            SpecCase(
                                preset,
                                fork_dir.name,
                                runner_dir.name,
                                handler_dir.name,
                                suite_dir.name,
                                case_dir.name,
                                case_dir,
                            )
                        )
    return out


def _load_state(case: SpecCase, types, fname: str) -> BeaconStateView | None:
    raw = case.read_ssz(fname)
    if raw is None:
        return None
    t = types.by_fork[case.fork].BeaconState
    return BeaconStateView(state=t.deserialize(raw), fork=case.fork)


def _roots_equal(cfg, types, got: BeaconStateView, want: BeaconStateView):
    g = got.hash_tree_root(types)
    w = want.hash_tree_root(types)
    return g == w, g, w


# operation handler -> (ssz file name, type attr, apply fn) bindings
# (beacon-node/test/spec/presets/operations.ts)
_OPERATION_BINDINGS = {
    "attestation": ("attestation", "Attestation", "process_attestation"),
    "attester_slashing": (
        "attester_slashing",
        "AttesterSlashing",
        "process_attester_slashing",
    ),
    "block_header": ("block", "BeaconBlock", "process_block_header"),
    "deposit": ("deposit", "Deposit", "process_deposit"),
    "proposer_slashing": (
        "proposer_slashing",
        "ProposerSlashing",
        "process_proposer_slashing",
    ),
    "voluntary_exit": (
        "voluntary_exit",
        "SignedVoluntaryExit",
        "process_voluntary_exit",
    ),
    "sync_aggregate": (
        "sync_aggregate",
        "SyncAggregate",
        "process_sync_aggregate",
    ),
    "bls_to_execution_change": (
        "address_change",
        "SignedBLSToExecutionChange",
        "process_bls_to_execution_change",
    ),
    "withdrawals": (
        "execution_payload",
        "ExecutionPayload",
        "process_withdrawals",
    ),
}


def run_operations_case(cfg, types, case: SpecCase) -> None:
    from ..statetransition import block as B

    binding = _OPERATION_BINDINGS.get(case.handler)
    if binding is None:
        raise NotImplementedError(f"operation {case.handler}")
    fname, type_name, fn_name = binding
    pre = _load_state(case, types, "pre")
    post = _load_state(case, types, "post")
    ns = types.by_fork[case.fork]
    op_t = getattr(ns, type_name, None) or getattr(types, type_name)
    op = op_t.deserialize(case.read_ssz(fname))
    ctx = BlockCtx(
        cfg, pre.state, types, int(ForkSeq[case.fork]), verify_signatures=True
    )
    fn = getattr(B, fn_name)
    try:
        fn(ctx, op)
        ok = True
    except (BlockProcessError, AssertionError, ValueError):
        ok = False
    if post is None:
        assert not ok, f"{case.path}: expected failure but op succeeded"
        return
    assert ok, f"{case.path}: operation failed unexpectedly"
    same, g, w = _roots_equal(cfg, types, pre, post)
    assert same, f"{case.path}: post root {g.hex()} != {w.hex()}"


# epoch-processing handler -> function over EpochTransitionCache
# (beacon-node/test/spec/presets/epoch_processing.ts)
_EPOCH_BINDINGS = {
    "justification_and_finalization": "process_justification_and_finalization",
    "inactivity_updates": "process_inactivity_updates",
    "rewards_and_penalties": "process_rewards_and_penalties",
    "registry_updates": "process_registry_updates",
    "slashings": "process_slashings",
    "eth1_data_reset": "process_eth1_data_reset",
    "effective_balance_updates": "process_effective_balance_updates",
    "slashings_reset": "process_slashings_reset",
    "randao_mixes_reset": "process_randao_mixes_reset",
    "historical_roots_update": "process_historical_roots_update",
    "historical_summaries_update": "process_historical_summaries_update",
    "participation_record_updates": "process_participation_record_updates",
    "participation_flag_updates": "process_participation_flag_updates",
    "sync_committee_updates": "process_sync_committee_updates",
    "pending_deposits": "process_pending_deposits",
    "pending_consolidations": "process_pending_consolidations",
}

_EPOCH_FNS_WITH_TYPES = {
    "process_justification_and_finalization",
    "process_historical_roots_update",
    "process_historical_summaries_update",
    "process_sync_committee_updates",
    "process_pending_deposits",
}


def run_epoch_processing_case(cfg, types, case: SpecCase) -> None:
    fn_name = _EPOCH_BINDINGS.get(case.handler)
    if fn_name is None:
        raise NotImplementedError(f"epoch step {case.handler}")
    pre = _load_state(case, types, "pre")
    post = _load_state(case, types, "post")
    cache = E.EpochTransitionCache(
        cfg, pre.state, int(ForkSeq[case.fork])
    )
    fn = getattr(E, fn_name)
    try:
        if fn_name in _EPOCH_FNS_WITH_TYPES:
            fn(cache, pre.state, types)
        else:
            fn(cache, pre.state)
        ok = True
    except (AssertionError, ValueError, BlockProcessError):
        ok = False
    if post is None:
        assert not ok, f"{case.path}: expected failure"
        return
    assert ok, f"{case.path}: epoch step failed unexpectedly"
    same, g, w = _roots_equal(cfg, types, pre, post)
    assert same, f"{case.path}: post root {g.hex()} != {w.hex()}"


def run_sanity_slots_case(cfg, types, case: SpecCase) -> None:
    pre = _load_state(case, types, "pre")
    post = _load_state(case, types, "post")
    meta = case.read_yaml("slots")
    n_slots = int(meta)
    process_slots(cfg, pre, int(pre.state.slot) + n_slots, types)
    same, g, w = _roots_equal(cfg, types, pre, post)
    assert same, f"{case.path}: post root {g.hex()} != {w.hex()}"


def _iter_blocks(case: SpecCase, types, fork: str):
    meta = case.read_yaml("meta") or {}
    n = int(meta.get("blocks_count", 0))
    ns = types.by_fork[fork]
    for i in range(n):
        raw = case.read_ssz(f"blocks_{i}")
        yield ns.SignedBeaconBlock.deserialize(raw)


def run_sanity_blocks_case(cfg, types, case: SpecCase) -> None:
    pre = _load_state(case, types, "pre")
    post = _load_state(case, types, "post")
    ok = True
    try:
        for block in _iter_blocks(case, types, case.fork):
            state_transition(
                cfg, pre, block, types,
                verify_state_root=True, verify_proposer=True,
                verify_signatures=True,
            )
    except (BlockProcessError, AssertionError, ValueError):
        ok = False
    if post is None:
        assert not ok, f"{case.path}: expected failure"
        return
    assert ok, f"{case.path}: block processing failed unexpectedly"
    same, g, w = _roots_equal(cfg, types, pre, post)
    assert same, f"{case.path}: post root {g.hex()} != {w.hex()}"


run_finality_case = run_sanity_blocks_case  # same shape, longer chains

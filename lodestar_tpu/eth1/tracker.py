"""Eth1 deposit-data tracker: follow contract logs, serve eth1 votes +
deposit proofs for block production.

Reference analog: Eth1DepositDataTracker (eth1/eth1DepositDataTracker.ts:57)
+ Eth1DataCache (eth1DataCache.ts) + eth1 vote selection
(utils/eth1Vote.ts) + Eth1ForBlockProduction (index.ts:60). The
provider side mirrors IEth1Provider (provider/eth1Provider.ts):
deposit logs + block headers over JSON-RPC; `MockEth1Provider` is the
test double (reference uses mocked providers in eth1 e2e tests).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from hashlib import sha256

from ..params import preset
from .deposit_tree import DepositTree

# keccak256("DepositEvent(bytes,bytes,bytes,bytes,bytes)") — constant
# from the deposit contract ABI, carried verbatim (no keccak dep needed)
DEPOSIT_EVENT_TOPIC = (
    "0x649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0889aa790803be39038c5"
)


class Eth1Error(Exception):
    pass


@dataclass
class DepositLog:
    index: int
    pubkey: bytes
    withdrawal_credentials: bytes
    amount: int
    signature: bytes
    block_number: int


@dataclass
class Eth1Block:
    number: int
    hash: bytes
    timestamp: int


def parse_deposit_event_data(data: bytes, block_number: int) -> DepositLog:
    """ABI-decode DepositEvent(bytes,bytes,bytes,bytes,bytes): head of
    five 32B offsets, each tail = len(32B) + padded payload."""

    def dyn(off_slot: int) -> bytes:
        off = int.from_bytes(data[off_slot * 32 : off_slot * 32 + 32], "big")
        n = int.from_bytes(data[off : off + 32], "big")
        return data[off + 32 : off + 32 + n]

    pubkey = dyn(0)
    wc = dyn(1)
    amount = int.from_bytes(dyn(2), "little")
    sig = dyn(3)
    index = int.from_bytes(dyn(4), "little")
    if len(pubkey) != 48 or len(wc) != 32 or len(sig) != 96:
        raise Eth1Error("malformed DepositEvent payload")
    return DepositLog(index, pubkey, wc, amount, sig, block_number)


class MockEth1Provider:
    """Scriptable in-memory eth1 chain (IEth1Provider test double)."""

    def __init__(self, genesis_time: int = 0, block_time: int = 14):
        self.logs: list[DepositLog] = []
        self.head_number = 0
        self.genesis_time = genesis_time
        self.block_time = block_time

    def add_deposit(
        self, pubkey: bytes, wc: bytes, amount: int, signature: bytes,
        block_number: int | None = None,
    ) -> None:
        bn = (
            block_number
            if block_number is not None
            else self.head_number
        )
        self.logs.append(
            DepositLog(len(self.logs), pubkey, wc, amount, signature, bn)
        )
        self.head_number = max(self.head_number, bn)

    def advance(self, n: int = 1) -> None:
        self.head_number += n

    async def get_block_number(self) -> int:
        return self.head_number

    async def get_block(self, number: int) -> Eth1Block:
        return Eth1Block(
            number=number,
            hash=sha256(b"eth1-block" + number.to_bytes(8, "little")).digest(),
            timestamp=self.genesis_time + number * self.block_time,
        )

    async def get_deposit_logs(self, from_block: int, to_block: int):
        return [
            log
            for log in self.logs
            if from_block <= log.block_number <= to_block
        ]


class JsonRpcEth1Provider:
    """IEth1Provider over eth JSON-RPC (provider/eth1Provider.ts)."""

    def __init__(self, rpc, deposit_contract: bytes):
        # rpc: execution.http.JsonRpcHttpClient
        self.rpc = rpc
        self.deposit_contract = deposit_contract

    async def get_block_number(self) -> int:
        return int(await self.rpc.call("eth_blockNumber", []), 16)

    async def get_block(self, number: int) -> Eth1Block:
        obj = await self.rpc.call(
            "eth_getBlockByNumber", [hex(number), False]
        )
        if obj is None:
            raise Eth1Error(f"eth1 block {number} not found")
        return Eth1Block(
            number=int(obj["number"], 16),
            hash=bytes.fromhex(obj["hash"].removeprefix("0x")),
            timestamp=int(obj["timestamp"], 16),
        )

    async def get_deposit_logs(self, from_block: int, to_block: int):
        logs = await self.rpc.call(
            "eth_getLogs",
            [
                {
                    "fromBlock": hex(from_block),
                    "toBlock": hex(to_block),
                    "address": "0x" + self.deposit_contract.hex(),
                    "topics": [DEPOSIT_EVENT_TOPIC],
                }
            ],
        )
        out = []
        for lg in logs:
            out.append(
                parse_deposit_event_data(
                    bytes.fromhex(lg["data"].removeprefix("0x")),
                    int(lg["blockNumber"], 16),
                )
            )
        return out


def _voting_period_start_time(cfg, state) -> int:
    from ..params import preset as _p

    p = _p()
    period_slots = p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH
    period_start_slot = int(state.slot) - int(state.slot) % period_slots
    return int(state.genesis_time) + period_start_slot * cfg.SECONDS_PER_SLOT


MAX_FOLLOWED_BLOCKS = 4096  # bound the followed-header window
GET_LOGS_CHUNK = 10_000  # blocks per eth_getLogs request (provider caps)


class Eth1DepositDataTracker:
    """Follows deposit logs into a DepositTree and answers block
    production's get_eth1_data_and_deposits (spec get_eth1_vote +
    deposit proof assembly)."""

    # polling backoff bounds (seconds): first failure waits BASE, each
    # consecutive failure doubles up to MAX (jittered), mirroring the
    # reference follow loop's error backoff
    BACKOFF_BASE = 1.0
    BACKOFF_MAX = 60.0

    def __init__(self, cfg, types, provider, clock=None):
        from ..resilience.clock import SYSTEM_CLOCK

        self.cfg = cfg
        self.types = types
        self.provider = provider
        self.clock = clock or SYSTEM_CLOCK
        self.tree = DepositTree()
        self.metrics = None  # lodestar_eth1_* family (node wiring)
        self.deposits: list[DepositLog] = []
        self.blocks: dict[int, Eth1Block] = {}  # followed eth1 blocks
        self._consecutive_failures = 0
        self._next_poll_at = 0.0  # monotonic deadline while backing off
        # Log-follow starts at the deposit contract's deployment block —
        # there can be no logs before it (ref eth1 follow loop seeds
        # from depositContractDeployBlock).
        self._synced_to = (
            getattr(cfg, "DEPOSIT_CONTRACT_DEPLOY_BLOCK", 0) - 1
        )

    # -- log following -----------------------------------------------------

    def _record_poll_failure(self) -> None:
        """Exponential backoff between failed polling rounds so a dead
        provider isn't hammered every slot; the next update() inside
        the window is a no-op instead of another doomed request."""
        from ..resilience import backoff_delay

        if self.metrics is not None:
            self.metrics.update_errors_total.inc()
        delay = backoff_delay(
            self._consecutive_failures,
            self.BACKOFF_BASE,
            self.BACKOFF_MAX,
            jitter="none",
        )
        self._consecutive_failures += 1
        self._next_poll_at = self.clock.monotonic() + delay

    async def update(self) -> None:
        """One polling round: fetch new logs up to the follow distance
        (eth1DepositDataTracker.ts update loop). getLogs is chunked
        (providers reject unbounded ranges) and headers are fetched only
        inside the eth1-vote candidate window, not for every followed
        block. Failed rounds back off exponentially (injectable clock)
        before the provider is polled again."""
        if self.clock.monotonic() < self._next_poll_at:
            return  # still backing off a previous provider failure
        try:
            head = await self.provider.get_block_number()
        except Exception:
            self._record_poll_failure()
            raise
        followed = max(0, head - self.cfg.ETH1_FOLLOW_DISTANCE)
        if self.metrics is not None:
            self.metrics.followed_block_number.set(followed)
        if followed <= self._synced_to:
            return
        # Logs first, headers after each chunk's logs: _synced_to
        # advances PER CHUNK so a mid-sync provider failure resumes
        # where it left off instead of re-raising on re-fetched logs;
        # re-delivered logs (index < len) are skipped idempotently.
        hdr_floor = max(followed - MAX_FOLLOWED_BLOCKS + 1, 0)
        start = self._synced_to + 1
        try:
            while start <= followed:
                end = min(start + GET_LOGS_CHUNK - 1, followed)
                logs = await self.provider.get_deposit_logs(start, end)
                for log in sorted(logs, key=lambda x: x.index):
                    if log.index < len(self.deposits):
                        continue  # re-delivered after a partial round
                    if log.index != len(self.deposits):
                        raise Eth1Error(
                            f"deposit log gap: got {log.index}, "
                            f"expected {len(self.deposits)}"
                        )
                    self.deposits.append(log)
                    self.tree.push(self._deposit_data_root(log))
                # Headers for this chunk's slice of the candidate window
                # (only the tail that can ever be an eth1-vote candidate),
                # fetched concurrently in bounded waves.
                h0 = max(start, hdr_floor)
                for wave in range(h0, end + 1, 64):
                    nums = range(wave, min(wave + 64, end + 1))
                    got = await asyncio.gather(
                        *(self.provider.get_block(bn) for bn in nums)
                    )
                    for blk in got:
                        self.blocks[blk.number] = blk
                self._synced_to = end
                start = end + 1
        except Exception:
            # _synced_to already advanced per completed chunk, so the
            # next round resumes where this one failed
            self._record_poll_failure()
            raise
        self._consecutive_failures = 0
        self._next_poll_at = 0.0
        while len(self.blocks) > MAX_FOLLOWED_BLOCKS:
            self.blocks.pop(min(self.blocks))

    def _deposit_data_root(self, log: DepositLog) -> bytes:
        dd = self.types.DepositData.default()
        dd.pubkey = log.pubkey
        dd.withdrawal_credentials = log.withdrawal_credentials
        dd.amount = log.amount
        dd.signature = log.signature
        return self.types.DepositData.hash_tree_root(dd)

    # -- block production --------------------------------------------------

    def _eth1_data_for_block(self, block: Eth1Block):
        count = sum(
            1 for d in self.deposits if d.block_number <= block.number
        )
        e = self.types.Eth1Data.default()
        e.deposit_root = self.tree.root_at(count)
        e.deposit_count = count
        e.block_hash = block.hash
        return e, count

    def get_eth1_vote(self, state):
        """Spec get_eth1_vote (utils/eth1Vote.ts): candidates are
        followed blocks inside the voting-period timestamp window whose
        deposit_count doesn't regress the state's; majority vote among
        those, else the newest candidate."""
        if not self.blocks:
            return state.eth1_data
        p = preset()
        period_start = _voting_period_start_time(self.cfg, state)
        lo = period_start - (
            self.cfg.ETH1_FOLLOW_DISTANCE
            * 2
            * self.cfg.SECONDS_PER_ETH1_BLOCK
        )
        hi = period_start - (
            self.cfg.ETH1_FOLLOW_DISTANCE * self.cfg.SECONDS_PER_ETH1_BLOCK
        )
        floor = int(state.eth1_data.deposit_count)
        candidates = []
        for b in sorted(self.blocks.values(), key=lambda b: b.number):
            if not (lo <= b.timestamp <= hi):
                continue
            data, count = self._eth1_data_for_block(b)
            if count < floor:
                continue
            candidates.append(data)
        if not candidates:
            return state.eth1_data
        t = self.types.Eth1Data
        valid = {t.serialize(c): c for c in candidates}
        tally: dict[bytes, int] = {}
        for vote in state.eth1_data_votes:
            key = t.serialize(vote)
            if key in valid:
                tally[key] = tally.get(key, 0) + 1
        if tally:
            best = max(tally.items(), key=lambda kv: kv[1])[0]
            return valid[best]
        return candidates[-1]

    def get_deposits(self, state, eth1_data) -> list:
        """Deposit objects (with proofs) the block must include:
        state.eth1_deposit_index .. min(count, index+MAX_DEPOSITS)."""
        p = preset()
        count = int(eth1_data.deposit_count)
        start = int(state.eth1_deposit_index)
        end = min(count, start + p.MAX_DEPOSITS)
        out = []
        for i in range(start, end):
            log = self.deposits[i]
            dep = self.types.Deposit.default()
            dd = self.types.DepositData.default()
            dd.pubkey = log.pubkey
            dd.withdrawal_credentials = log.withdrawal_credentials
            dd.amount = log.amount
            dd.signature = log.signature
            dep.data = dd
            dep.proof = self.tree.branch(i, count)
            out.append(dep)
        return out

    async def get_eth1_data_and_deposits(self, state):
        """(eth1_data, deposits) for produceBlockBody (reference:
        Eth1ForBlockProduction.getEth1DataAndDeposits). A failed
        polling round must not fail block production: the vote falls
        back to what the tracker already follows (worst case the
        state's own eth1_data — the spec default when no candidates
        qualify)."""
        try:
            await self.update()
        except Exception:
            # already metered + backoff-scheduled by update(); serve
            # from the last synced window
            pass
        eth1_data = self.get_eth1_vote(state)
        deposits = self.get_deposits(state, eth1_data)
        return eth1_data, deposits

"""Eth1 deposit tracking: contract-log following + deposit merkle tree.

Reference analog: beacon-node/src/eth1/ — Eth1DepositDataTracker
(eth1DepositDataTracker.ts:57), deposit tree utilities (utils/deposits.ts,
utils/eth1Vote.ts), JSON-RPC provider (provider/eth1Provider.ts).
"""

from .deposit_tree import DepositTree
from .tracker import Eth1DepositDataTracker, Eth1Error, MockEth1Provider

__all__ = [
    "DepositTree",
    "Eth1DepositDataTracker",
    "Eth1Error",
    "MockEth1Provider",
]

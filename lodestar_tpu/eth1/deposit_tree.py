"""Append-only deposit merkle tree (depth 32, mix-in count root).

Reference analog: the deposit tree the reference maintains from
contract logs (eth1/utils/deposits.ts over @chainsafe/
persistent-merkle-tree) matching the deposit contract's incremental
merkle root. Roots/proofs follow the spec: root =
hash(merkle_root_of_2^32_padded_leaves ++ count_le32), proofs are
DEPOSIT_CONTRACT_TREE_DEPTH+1 long with the count leaf last
(is_valid_merkle_branch over depth+1).
"""

from __future__ import annotations

from hashlib import sha256

DEPOSIT_CONTRACT_TREE_DEPTH = 32

_ZERO = [b"\x00" * 32]
for _ in range(DEPOSIT_CONTRACT_TREE_DEPTH + 1):
    _ZERO.append(sha256(_ZERO[-1] + _ZERO[-1]).digest())


def _h(a: bytes, b: bytes) -> bytes:
    return sha256(a + b).digest()


class DepositTree:
    """Keeps all leaves; computes roots/branches with zero-subtree
    shortcuts (node count touched per op is O(log n), computed lazily
    with a per-(level,index) memo invalidated on append path)."""

    def __init__(self):
        self.leaves: list[bytes] = []
        self._memo: dict[tuple[int, int], bytes] = {}

    def __len__(self) -> int:
        return len(self.leaves)

    def push(self, leaf: bytes) -> None:
        """Append a deposit-data root."""
        idx = len(self.leaves)
        self.leaves.append(bytes(leaf))
        # invalidate the path of the new leaf
        for level in range(DEPOSIT_CONTRACT_TREE_DEPTH + 1):
            self._memo.pop((level, idx >> level), None)

    def _node(self, level: int, idx: int, size: int) -> bytes:
        """Root of the subtree at (level, idx) over the first `size`
        leaves, padded with zero subtrees."""
        start = idx << level
        if start >= size:
            return _ZERO[level]
        full_under = size >= ((idx + 1) << level)
        key = (level, idx)
        if full_under and key in self._memo:
            return self._memo[key]
        if level == 0:
            out = self.leaves[idx]
        else:
            out = _h(
                self._node(level - 1, 2 * idx, size),
                self._node(level - 1, 2 * idx + 1, size),
            )
        if full_under:
            self._memo[key] = out
        return out

    def root_at(self, size: int) -> bytes:
        """Spec deposit root for the first `size` leaves (mix-in count)."""
        inner = self._node(DEPOSIT_CONTRACT_TREE_DEPTH, 0, size)
        return _h(inner, size.to_bytes(32, "little"))

    @property
    def root(self) -> bytes:
        return self.root_at(len(self.leaves))

    def branch(self, index: int, size: int) -> list[bytes]:
        """Proof of leaf `index` against root_at(size): depth-32 sibling
        path + the count leaf (spec Deposit.proof layout)."""
        assert 0 <= index < size <= len(self.leaves)
        out = []
        idx = index
        for level in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            out.append(self._node(level, idx ^ 1, size))
            idx >>= 1
        out.append(size.to_bytes(32, "little"))
        return out

    def finalized_roots(self, size: int | None = None) -> list[bytes]:
        """EIP-4881 snapshot `finalized` vector: roots of the maximal
        full subtrees covering leaves [0, size), left to right (one per
        set bit of size, descending subtree size). A consumer can
        reconstruct a DepositTreeSnapshot from this list + count."""
        if size is None:
            size = len(self.leaves)
        assert 0 <= size <= len(self.leaves)
        out: list[bytes] = []
        offset = 0
        for level in range(DEPOSIT_CONTRACT_TREE_DEPTH, -1, -1):
            if (size >> level) & 1:
                out.append(self._node(level, offset >> level, size))
                offset += 1 << level
        return out

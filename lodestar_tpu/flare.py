"""Flare: ops commands for non-standard (dangerous) actions.

Reference analog: packages/flare — a small CLI for fault injection and
testnet surgery, e.g. `selfSlashProposer` (src/cmds/selfSlashProposer.ts)
which signs two conflicting blocks for a validator to force a slashing,
and a matching attester variant. Used by the sim harness and operators
to exercise slashing paths end-to-end.
"""

from __future__ import annotations

from .crypto.bls.signature import sign
from .params import DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER, preset
from .statetransition import util
from .statetransition.block import compute_signing_root, get_domain


def self_slash_proposer(cfg, types, state, validator_index: int, sk: int,
                        slot: int | None = None):
    """Build a ProposerSlashing by signing two conflicting headers for
    `validator_index` (flare selfSlashProposer analog). Returns the
    ProposerSlashing value, ready for the op pool / gossip."""
    s = int(slot if slot is not None else state.slot)
    domain = get_domain(
        cfg, state, DOMAIN_BEACON_PROPOSER, util.compute_epoch_at_slot(s)
    )

    def mk(graffiti_root: bytes):
        h = types.BeaconBlockHeader.default()
        h.slot = s
        h.proposer_index = validator_index
        h.parent_root = b"\x00" * 32
        h.state_root = b"\x00" * 32
        h.body_root = graffiti_root
        sh = types.SignedBeaconBlockHeader.default()
        sh.message = h
        root = compute_signing_root(types.BeaconBlockHeader, h, domain)
        sh.signature = sign(sk, root)
        return sh

    slashing = types.ProposerSlashing.default()
    slashing.signed_header_1 = mk(b"\x01" * 32)
    slashing.signed_header_2 = mk(b"\x02" * 32)
    return slashing


def self_slash_attester(cfg, types, state, validator_index: int, sk: int,
                        target_epoch: int | None = None):
    """Build an AttesterSlashing from two contradictory attestations
    (double vote) by `validator_index`."""
    epoch = int(
        target_epoch
        if target_epoch is not None
        else util.get_current_epoch(state)
    )
    domain = get_domain(cfg, state, DOMAIN_BEACON_ATTESTER, epoch)

    def mk(beacon_root: bytes):
        data = types.AttestationData.default()
        data.slot = epoch * preset().SLOTS_PER_EPOCH
        data.index = 0
        data.beacon_block_root = beacon_root
        data.source = state.current_justified_checkpoint
        data.target.epoch = epoch
        data.target.root = beacon_root
        att = types.IndexedAttestation.default()
        att.attesting_indices = [validator_index]
        att.data = data
        root = compute_signing_root(types.AttestationData, data, domain)
        att.signature = sign(sk, root)
        return att

    slashing = types.AttesterSlashing.default()
    slashing.attestation_1 = mk(b"\x0a" * 32)
    slashing.attestation_2 = mk(b"\x0b" * 32)
    return slashing

"""Route table: single source of truth for server + client.

Reference analog: packages/api/src/beacon/routes/ — each endpoint
declared once with method, path template, and impl binding; the server
registers them (api/utils/server/) and the client generates callers
(api/utils/client/).
"""

from __future__ import annotations

from dataclasses import dataclass

from .impl import ApiError  # re-export for package __init__


@dataclass(frozen=True)
class Route:
    operation_id: str
    method: str  # GET | POST
    path: str  # template with {param} segments
    impl_name: str  # method on BeaconApiImpl
    wrap_data: bool = True  # beacon-api {"data": ...} envelope
    raw_body: bool = False  # pass the parsed JSON body through as-is
    query_params: tuple = ()  # query-string params appended in order
    # idempotent hot GET whose body is a pure function of the current
    # head: serialized once into the api/overload.py response cache,
    # invalidated by the chain event bus (ISSUE 20)
    cacheable: bool = False


ROUTES: list[Route] = [
    # beacon
    Route(
        "getGenesis",
        "GET",
        "/eth/v1/beacon/genesis",
        "get_genesis",
        cacheable=True,
    ),
    Route(
        "getStateFork",
        "GET",
        "/eth/v1/beacon/states/{state_id}/fork",
        "get_state_fork",
        cacheable=True,
    ),
    Route(
        "getStateFinalityCheckpoints",
        "GET",
        "/eth/v1/beacon/states/{state_id}/finality_checkpoints",
        "get_state_finality_checkpoints",
        cacheable=True,
    ),
    Route(
        "getStateValidators",
        "GET",
        "/eth/v1/beacon/states/{state_id}/validators",
        "get_state_validators",
    ),
    Route(
        "getBlockHeader",
        "GET",
        "/eth/v1/beacon/headers/{block_id}",
        "get_block_header",
        cacheable=True,
    ),
    # validator
    Route(
        "getProposerDuties",
        "GET",
        "/eth/v1/validator/duties/proposer/{epoch}",
        "get_proposer_duties",
        cacheable=True,
    ),
    Route(
        "getAttesterDuties",
        "POST",
        "/eth/v1/validator/duties/attester/{epoch}",
        "get_attester_duties",
    ),
    Route(
        "getBlockV2",
        "GET",
        "/eth/v2/beacon/blocks/{block_id}",
        "get_block_v2",
        wrap_data=False,  # impl returns the {version, data} envelope
    ),
    Route(
        "getBlockRoot",
        "GET",
        "/eth/v1/beacon/blocks/{block_id}/root",
        "get_block_root",
        cacheable=True,
    ),
    Route(
        "publishBlock",
        "POST",
        "/eth/v1/beacon/blocks",
        "publish_block_json",
        raw_body=True,
    ),
    Route(
        "publishBlindedBlock",
        "POST",
        "/eth/v1/beacon/blinded_blocks",
        "publish_blinded_block_json",
        raw_body=True,
    ),
    Route(
        "publishBlindedBlockV2",
        "POST",
        "/eth/v2/beacon/blinded_blocks",
        "publish_blinded_block_json",
        raw_body=True,
    ),
    # pools
    Route(
        "submitPoolAttestations",
        "POST",
        "/eth/v1/beacon/pool/attestations",
        "submit_pool_attestations",
        raw_body=True,
    ),
    Route(
        "getPoolAttestations",
        "GET",
        "/eth/v1/beacon/pool/attestations",
        "get_pool_attestations",
    ),
    Route(
        "submitPoolVoluntaryExit",
        "POST",
        "/eth/v1/beacon/pool/voluntary_exits",
        "submit_pool_voluntary_exit",
        raw_body=True,
    ),
    Route(
        "submitPoolAttesterSlashings",
        "POST",
        "/eth/v1/beacon/pool/attester_slashings",
        "submit_pool_attester_slashing",
        raw_body=True,
    ),
    Route(
        "submitPoolProposerSlashings",
        "POST",
        "/eth/v1/beacon/pool/proposer_slashings",
        "submit_pool_proposer_slashing",
        raw_body=True,
    ),
    # validator (continued)
    Route(
        "produceAttestationData",
        "GET",
        "/eth/v1/validator/attestation_data",
        "produce_attestation_data",
        query_params=("slot", "committee_index"),
    ),
    Route(
        "produceBlockV2",
        "GET",
        "/eth/v2/validator/blocks/{slot}",
        "produce_block_v2",
        wrap_data=False,  # impl returns the {version, data} envelope
        query_params=("randao_reveal", "graffiti"),
    ),
    Route(
        "produceBlockV3",
        "GET",
        "/eth/v3/validator/blocks/{slot}",
        "produce_block_v3",
        wrap_data=False,
        query_params=(
            "randao_reveal",
            "graffiti",
            "skip_randao_verification",
            "builder_boost_factor",
        ),
    ),
    # debug
    Route(
        "getStateV2",
        "GET",
        "/eth/v2/debug/beacon/states/{state_id}",
        "get_state_v2",
        wrap_data=False,
    ),
    Route(
        "getDebugForkChoice",
        "GET",
        "/eth/v1/debug/fork_choice",
        "get_debug_fork_choice",
        wrap_data=False,
    ),
    # light client
    Route(
        "getLightClientBootstrap",
        "GET",
        "/eth/v1/beacon/light_client/bootstrap/{block_root}",
        "get_light_client_bootstrap",
        cacheable=True,
    ),
    Route(
        "getLightClientFinalityUpdate",
        "GET",
        "/eth/v1/beacon/light_client/finality_update",
        "get_light_client_finality_update",
        cacheable=True,
    ),
    Route(
        "getLightClientOptimisticUpdate",
        "GET",
        "/eth/v1/beacon/light_client/optimistic_update",
        "get_light_client_optimistic_update",
        cacheable=True,
    ),
    # beacon: state detail
    Route(
        "getStateRoot",
        "GET",
        "/eth/v1/beacon/states/{state_id}/root",
        "get_state_root",
    ),
    Route(
        "getStateValidatorBalances",
        "GET",
        "/eth/v1/beacon/states/{state_id}/validator_balances",
        "get_state_validator_balances",
    ),
    Route(
        "getEpochCommittees",
        "GET",
        "/eth/v1/beacon/states/{state_id}/committees",
        "get_epoch_committees",
        query_params=("epoch", "index", "slot"),
    ),
    Route(
        "getEpochSyncCommittees",
        "GET",
        "/eth/v1/beacon/states/{state_id}/sync_committees",
        "get_epoch_sync_committees",
        query_params=("epoch",),
    ),
    Route(
        "getBlobSidecars",
        "GET",
        "/eth/v1/beacon/blob_sidecars/{block_id}",
        "get_blob_sidecars",
    ),
    Route(
        "getBlockRewards",
        "GET",
        "/eth/v1/beacon/rewards/blocks/{block_id}",
        "get_block_rewards",
    ),
    # pools (continued)
    Route(
        "submitPoolSyncCommitteeSignatures",
        "POST",
        "/eth/v1/beacon/pool/sync_committees",
        "submit_pool_sync_committee_signatures",
        raw_body=True,
    ),
    Route(
        "submitPoolBLSToExecutionChanges",
        "POST",
        "/eth/v1/beacon/pool/bls_to_execution_changes",
        "submit_pool_bls_changes",
        raw_body=True,
    ),
    # validator: aggregation + sync committee + registrations
    Route(
        "getAggregatedAttestation",
        "GET",
        "/eth/v1/validator/aggregate_attestation",
        "get_aggregated_attestation",
        query_params=("slot", "attestation_data_root"),
    ),
    Route(
        "publishAggregateAndProofs",
        "POST",
        "/eth/v1/validator/aggregate_and_proofs",
        "publish_aggregate_and_proofs",
        raw_body=True,
    ),
    Route(
        "prepareBeaconCommitteeSubnet",
        "POST",
        "/eth/v1/validator/beacon_committee_subscriptions",
        "prepare_beacon_committee_subnet",
        raw_body=True,
    ),
    Route(
        "prepareSyncCommitteeSubnets",
        "POST",
        "/eth/v1/validator/sync_committee_subscriptions",
        "prepare_sync_committee_subnets",
        raw_body=True,
    ),
    Route(
        "registerValidator",
        "POST",
        "/eth/v1/validator/register_validator",
        "register_validator",
        raw_body=True,
    ),
    Route(
        "prepareBeaconProposer",
        "POST",
        "/eth/v1/validator/prepare_beacon_proposer",
        "prepare_beacon_proposer",
        raw_body=True,
    ),
    Route(
        "getLiveness",
        "POST",
        "/eth/v1/validator/liveness/{epoch}",
        "get_liveness",
        raw_body=True,
    ),
    Route(
        "getSyncCommitteeDuties",
        "POST",
        "/eth/v1/validator/duties/sync/{epoch}",
        "get_sync_committee_duties",
        raw_body=True,
    ),
    Route(
        "produceSyncCommitteeContribution",
        "GET",
        "/eth/v1/validator/sync_committee_contribution",
        "produce_sync_committee_contribution",
        query_params=("slot", "subcommittee_index", "beacon_block_root"),
    ),
    Route(
        "publishContributionAndProofs",
        "POST",
        "/eth/v1/validator/contribution_and_proofs",
        "publish_contribution_and_proofs",
        raw_body=True,
    ),
    # node
    Route("getHealth", "GET", "/eth/v1/node/health", "get_health", wrap_data=False),
    Route("getNodeVersion", "GET", "/eth/v1/node/version", "get_version"),
    Route("getSyncingStatus", "GET", "/eth/v1/node/syncing", "get_syncing"),
    Route("getNetworkIdentity", "GET", "/eth/v1/node/identity", "get_identity"),
    Route("getPeers", "GET", "/eth/v1/node/peers", "get_peers"),
    Route(
        "getPeer", "GET", "/eth/v1/node/peers/{peer_id}", "get_peer"
    ),
    # config
    Route(
        "getSpec",
        "GET",
        "/eth/v1/config/spec",
        "get_spec",
        cacheable=True,
    ),
    Route(
        "getForkSchedule",
        "GET",
        "/eth/v1/config/fork_schedule",
        "get_fork_schedule",
        cacheable=True,
    ),
    Route(
        "getDepositContract",
        "GET",
        "/eth/v1/config/deposit_contract",
        "get_deposit_contract",
        cacheable=True,
    ),
    Route(
        "getBlockHeaders",
        "GET",
        "/eth/v1/beacon/headers",
        "get_block_headers",
        query_params=("slot", "parent_root"),
        cacheable=True,
    ),
    Route(
        "getDepositSnapshot",
        "GET",
        "/eth/v1/beacon/deposit_snapshot",
        "get_deposit_snapshot",
    ),
    Route(
        "getStateValidator",
        "GET",
        "/eth/v1/beacon/states/{state_id}/validators/{validator_id}",
        "get_state_validator",
    ),
    Route(
        "getStateRandao",
        "GET",
        "/eth/v1/beacon/states/{state_id}/randao",
        "get_state_randao",
        query_params=("epoch",),
    ),
    Route(
        "getBlockAttestations",
        "GET",
        "/eth/v1/beacon/blocks/{block_id}/attestations",
        "get_block_attestations",
    ),
    Route(
        "getPoolAttesterSlashings",
        "GET",
        "/eth/v1/beacon/pool/attester_slashings",
        "get_pool_attester_slashings",
    ),
    Route(
        "getPoolProposerSlashings",
        "GET",
        "/eth/v1/beacon/pool/proposer_slashings",
        "get_pool_proposer_slashings",
    ),
    Route(
        "getPoolVoluntaryExits",
        "GET",
        "/eth/v1/beacon/pool/voluntary_exits",
        "get_pool_voluntary_exits",
    ),
    Route(
        "getPoolBLSToExecutionChanges",
        "GET",
        "/eth/v1/beacon/pool/bls_to_execution_changes",
        "get_pool_bls_changes",
    ),
    Route(
        "getPeerCount",
        "GET",
        "/eth/v1/node/peer_count",
        "get_peer_count",
    ),
    Route(
        "getAttestationsRewards",
        "POST",
        "/eth/v1/beacon/rewards/attestations/{epoch}",
        "get_attestations_rewards",
        raw_body=True,
    ),
    Route(
        "getSyncCommitteeRewards",
        "POST",
        "/eth/v1/beacon/rewards/sync_committee/{block_id}",
        "get_sync_committee_rewards",
        raw_body=True,
    ),
    # lodestar admin namespace (routes/lodestar.ts)
    Route(
        "writeProfile",
        "POST",
        "/eth/v1/lodestar/write_profile",
        "write_profile",
        query_params=("duration",),
    ),
    Route(
        "writeHeapdump",
        "POST",
        "/eth/v1/lodestar/write_heapdump",
        "write_heapdump",
    ),
    Route(
        "getGossipQueueItems",
        "GET",
        "/eth/v1/lodestar/gossip_queue_items",
        "get_gossip_queue_items",
    ),
    Route(
        "getStateCacheItems",
        "GET",
        "/eth/v1/lodestar/state_cache_items",
        "get_state_cache_items",
    ),
    Route(
        "getGossipPeerScoreStats",
        "GET",
        "/eth/v1/lodestar/gossip_peer_score_stats",
        "get_gossip_peer_score_stats",
    ),
    Route(
        "getSyncChainsDebugState",
        "GET",
        "/eth/v1/lodestar/sync_chains_debug_state",
        "get_sync_chains_debug_state",
    ),
    Route(
        "getBlockImportTraces",
        "GET",
        "/eth/v1/lodestar/block_import_traces",
        "get_block_import_traces",
    ),
    Route(
        "writeDeviceTrace",
        "POST",
        "/eth/v1/lodestar/device_trace",
        "device_trace",
        query_params=("duration_ms",),
    ),
    # proof namespace (routes/proof.ts)
    Route(
        "getStateProof",
        "GET",
        "/eth/v0/beacon/proof/state/{state_id}",
        "get_state_proof",
        query_params=("field",),
    ),
    Route(
        "getBlockProof",
        "GET",
        "/eth/v0/beacon/proof/block/{block_id}",
        "get_block_proof",
        query_params=("field",),
    ),
]


def match_route(method: str, path: str):
    """Returns (route, params) or None."""
    parts = [p for p in path.split("/") if p]
    for route in ROUTES:
        if route.method != method:
            continue
        tparts = [p for p in route.path.split("/") if p]
        if len(tparts) != len(parts):
            continue
        params = {}
        ok = True
        for t, p in zip(tparts, parts):
            if t.startswith("{") and t.endswith("}"):
                params[t[1:-1]] = p
            elif t != p:
                ok = False
                break
        if ok:
            return route, params
    return None

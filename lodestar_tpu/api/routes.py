"""Route table: single source of truth for server + client.

Reference analog: packages/api/src/beacon/routes/ — each endpoint
declared once with method, path template, and impl binding; the server
registers them (api/utils/server/) and the client generates callers
(api/utils/client/).
"""

from __future__ import annotations

from dataclasses import dataclass

from .impl import ApiError  # re-export for package __init__


@dataclass(frozen=True)
class Route:
    operation_id: str
    method: str  # GET | POST
    path: str  # template with {param} segments
    impl_name: str  # method on BeaconApiImpl
    wrap_data: bool = True  # beacon-api {"data": ...} envelope


ROUTES: list[Route] = [
    # beacon
    Route("getGenesis", "GET", "/eth/v1/beacon/genesis", "get_genesis"),
    Route(
        "getStateFork",
        "GET",
        "/eth/v1/beacon/states/{state_id}/fork",
        "get_state_fork",
    ),
    Route(
        "getStateFinalityCheckpoints",
        "GET",
        "/eth/v1/beacon/states/{state_id}/finality_checkpoints",
        "get_state_finality_checkpoints",
    ),
    Route(
        "getStateValidators",
        "GET",
        "/eth/v1/beacon/states/{state_id}/validators",
        "get_state_validators",
    ),
    Route(
        "getBlockHeader",
        "GET",
        "/eth/v1/beacon/headers/{block_id}",
        "get_block_header",
    ),
    # validator
    Route(
        "getProposerDuties",
        "GET",
        "/eth/v1/validator/duties/proposer/{epoch}",
        "get_proposer_duties",
    ),
    Route(
        "getAttesterDuties",
        "POST",
        "/eth/v1/validator/duties/attester/{epoch}",
        "get_attester_duties",
    ),
    # node
    Route("getHealth", "GET", "/eth/v1/node/health", "get_health", wrap_data=False),
    Route("getNodeVersion", "GET", "/eth/v1/node/version", "get_version"),
    Route("getSyncingStatus", "GET", "/eth/v1/node/syncing", "get_syncing"),
    # config
    Route("getSpec", "GET", "/eth/v1/config/spec", "get_spec"),
]


def match_route(method: str, path: str):
    """Returns (route, params) or None."""
    parts = [p for p in path.split("/") if p]
    for route in ROUTES:
        if route.method != method:
            continue
        tparts = [p for p in route.path.split("/") if p]
        if len(tparts) != len(parts):
            continue
        params = {}
        ok = True
        for t, p in zip(tparts, parts):
            if t.startswith("{") and t.endswith("}"):
                params[t[1:-1]] = p
            elif t != p:
                ok = False
                break
        if ok:
            return route, params
    return None

"""Beacon node REST API: typed routes, server, client.

Reference analog: packages/api (typed endpoint definitions,
src/beacon/routes/*) + beacon-node/src/api/{impl,rest} (route business
logic over the chain, fastify server at rest/index.ts:38). Routes are
defined once (routes.py) and drive both the HTTP server (server.py)
and the client (client.py) — the reference's single-source-of-truth
design.
"""

from .routes import ROUTES, ApiError
from .server import BeaconRestApiServer
from .client import ApiClient

__all__ = ["ROUTES", "ApiError", "BeaconRestApiServer", "ApiClient"]

"""Beacon API HTTP client.

Reference analog: packages/api/src/utils/client/httpClient.ts:74 —
route-table-driven callers with base-url fallback and timeouts; used by
the validator client to talk to the beacon node.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .impl import ApiError
from .routes import ROUTES


class ApiClient:
    def __init__(self, base_urls, timeout: float = 10.0):
        if isinstance(base_urls, str):
            base_urls = [base_urls]
        self.base_urls = [u.rstrip("/") for u in base_urls]
        self.timeout = timeout
        self._routes = {r.operation_id: r for r in ROUTES}

    def call(self, operation_id: str, params=None, body=None):
        route = self._routes[operation_id]
        path = route.path
        query = []
        for k, v in (params or {}).items():
            if "{" + k + "}" in path:
                path = path.replace("{" + k + "}", str(v))
            else:
                # params not in the path template go to the query
                # string (the server fills route.query_params from it)
                from urllib.parse import quote

                query.append(f"{quote(str(k))}={quote(str(v))}")
        if query:
            path += "?" + "&".join(query)
        data = json.dumps(body).encode() if body is not None else None
        last_err = None
        for base in self.base_urls:  # fallback URLs (httpClient.ts)
            req = urllib.request.Request(
                base + path,
                data=data,
                method=route.method,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout
                ) as resp:
                    if resp.status == 200 and resp.length in (0, None) and (
                        operation_id == "getHealth"
                    ):
                        return resp.status
                    payload = resp.read()
                    if not payload:
                        return resp.status
                    out = json.loads(payload)
                    return out.get("data", out) if route.wrap_data else out
            except urllib.error.HTTPError as e:
                try:
                    err = json.loads(e.read())
                    raise ApiError(
                        e.code, err.get("message", str(e))
                    ) from None
                except (ValueError, KeyError):
                    raise ApiError(e.code, str(e)) from None
            except urllib.error.URLError as e:
                last_err = e
                continue
        raise ApiError(503, f"all base urls failed: {last_err}")

    # sugar for common calls
    def get_genesis(self):
        return self.call("getGenesis")

    def get_syncing(self):
        return self.call("getSyncingStatus")

    def get_proposer_duties(self, epoch: int):
        return self.call("getProposerDuties", {"epoch": epoch})

    def get_attester_duties(self, epoch: int, indices: list[int]):
        return self.call(
            "getAttesterDuties",
            {"epoch": epoch},
            body=[str(i) for i in indices],
        )

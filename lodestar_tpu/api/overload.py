"""Serving-tier fault domain: admission control, brownout shedding,
and the head-keyed response cache (ISSUE 20, ROADMAP item 3a).

Reference analog: the rest server's bodyLimit / activeSockets plumbing
(beacon-node/src/api/rest) plus the QoS treatment the device executor
(device/executor.py) already gives the accelerator — here the scarce
resource is the node's single asyncio loop, which imports blocks and
schedules duties on the same thread every REST bridge hop lands on.
At north-star scale ("millions of light clients", arxiv 2302.00418's
signature-load model) read overload is the NORMAL regime, so every
request is classified into a QoS class and the cheap classes are shed
first, on an accounted ledger, never silently:

* classes — validator-duty > consensus-read > light-client/
  historical-read > admin/debug (`ROUTE_CLASSES`, completeness pinned
  by tests/test_api_overload.py);
* admission — per-class token buckets + concurrency budgets with
  queue-with-deadline semantics; refusals are 429/503 + Retry-After
  and land on the `lodestar_api_sheds_total{cls,reason}` ledger
  exactly like `lodestar_device_sheds_total`;
* brownout ladder — an event-loop-lag probe trips a per-class
  resilience/breaker.py circuit, cheapest class first, recovering
  half-open, so the loop protects block import before reads;
* response cache — hot idempotent routes serialize once per head; the
  ChainEventEmitter's head/finality events invalidate, and under
  brownout a stale body is served rather than a refusal
  (stale-while-revalidate).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field

from ..resilience.breaker import (
    BREAKER_STATE_INDEX,
    BreakerState,
    CircuitBreaker,
)
from ..resilience.clock import SYSTEM_CLOCK

# ---------------------------------------------------------------------------
# QoS classes + route classification
# ---------------------------------------------------------------------------

CLS_DUTY = "duty"  # validator duties + consensus message intake
CLS_CONSENSUS = "consensus"  # cheap head/consensus reads, node status
CLS_LIGHT = "light"  # light-client + historical/heavy reads
CLS_ADMIN = "admin"  # debug + lodestar admin namespace
CLS_CONN = "conn"  # pre-route: connection refused at the pool

CLASSES = (CLS_DUTY, CLS_CONSENSUS, CLS_LIGHT, CLS_ADMIN)

# the SSE stream is not in ROUTES (the server special-cases it); it
# still needs a class for its admission + shed accounting
EVENTSTREAM_OP = "eventstream"

# every operation_id in api/routes.py maps to EXACTLY one class —
# tests/test_api_overload.py fails when a new route lands unmapped,
# so nothing ever ships in the implicit (most-shed) default class
ROUTE_CLASSES: dict[str, str] = {
    # validator-duty: the VC-facing hot path — shedding these misses
    # duties, so they are the LAST class the ladder touches (never)
    "getProposerDuties": CLS_DUTY,
    "getAttesterDuties": CLS_DUTY,
    "getSyncCommitteeDuties": CLS_DUTY,
    "getLiveness": CLS_DUTY,
    "produceAttestationData": CLS_DUTY,
    "produceBlockV2": CLS_DUTY,
    "produceBlockV3": CLS_DUTY,
    "produceSyncCommitteeContribution": CLS_DUTY,
    "getAggregatedAttestation": CLS_DUTY,
    "publishBlock": CLS_DUTY,
    "publishBlindedBlock": CLS_DUTY,
    "publishBlindedBlockV2": CLS_DUTY,
    "publishAggregateAndProofs": CLS_DUTY,
    "publishContributionAndProofs": CLS_DUTY,
    "prepareBeaconCommitteeSubnet": CLS_DUTY,
    "prepareSyncCommitteeSubnets": CLS_DUTY,
    "prepareBeaconProposer": CLS_DUTY,
    "registerValidator": CLS_DUTY,
    "submitPoolAttestations": CLS_DUTY,
    "submitPoolSyncCommitteeSignatures": CLS_DUTY,
    "submitPoolVoluntaryExit": CLS_DUTY,
    "submitPoolAttesterSlashings": CLS_DUTY,
    "submitPoolProposerSlashings": CLS_DUTY,
    "submitPoolBLSToExecutionChanges": CLS_DUTY,
    # consensus-read: cheap current-head reads and node/config status
    "getGenesis": CLS_CONSENSUS,
    "getStateFork": CLS_CONSENSUS,
    "getStateFinalityCheckpoints": CLS_CONSENSUS,
    "getBlockHeader": CLS_CONSENSUS,
    "getBlockHeaders": CLS_CONSENSUS,
    "getBlockV2": CLS_CONSENSUS,
    "getBlockRoot": CLS_CONSENSUS,
    "getBlockAttestations": CLS_CONSENSUS,
    "getPoolAttestations": CLS_CONSENSUS,
    "getPoolAttesterSlashings": CLS_CONSENSUS,
    "getPoolProposerSlashings": CLS_CONSENSUS,
    "getPoolVoluntaryExits": CLS_CONSENSUS,
    "getPoolBLSToExecutionChanges": CLS_CONSENSUS,
    "getHealth": CLS_CONSENSUS,
    "getNodeVersion": CLS_CONSENSUS,
    "getSyncingStatus": CLS_CONSENSUS,
    "getNetworkIdentity": CLS_CONSENSUS,
    "getPeers": CLS_CONSENSUS,
    "getPeer": CLS_CONSENSUS,
    "getPeerCount": CLS_CONSENSUS,
    "getSpec": CLS_CONSENSUS,
    "getForkSchedule": CLS_CONSENSUS,
    "getDepositContract": CLS_CONSENSUS,
    # light-client / historical: the "millions of light clients" front
    # door plus full-state walks — first useful class to shed
    "getLightClientBootstrap": CLS_LIGHT,
    "getLightClientFinalityUpdate": CLS_LIGHT,
    "getLightClientOptimisticUpdate": CLS_LIGHT,
    "getStateProof": CLS_LIGHT,
    "getBlockProof": CLS_LIGHT,
    "getStateValidators": CLS_LIGHT,
    "getStateValidator": CLS_LIGHT,
    "getStateValidatorBalances": CLS_LIGHT,
    "getEpochCommittees": CLS_LIGHT,
    "getEpochSyncCommittees": CLS_LIGHT,
    "getStateRandao": CLS_LIGHT,
    "getStateRoot": CLS_LIGHT,
    "getBlobSidecars": CLS_LIGHT,
    "getBlockRewards": CLS_LIGHT,
    "getAttestationsRewards": CLS_LIGHT,
    "getSyncCommitteeRewards": CLS_LIGHT,
    "getDepositSnapshot": CLS_LIGHT,
    EVENTSTREAM_OP: CLS_LIGHT,
    # admin/debug: operator introspection — cheapest to live without
    "getStateV2": CLS_ADMIN,
    "getDebugForkChoice": CLS_ADMIN,
    "writeProfile": CLS_ADMIN,
    "writeHeapdump": CLS_ADMIN,
    "getGossipQueueItems": CLS_ADMIN,
    "getStateCacheItems": CLS_ADMIN,
    "getGossipPeerScoreStats": CLS_ADMIN,
    "getSyncChainsDebugState": CLS_ADMIN,
    "getBlockImportTraces": CLS_ADMIN,
    "writeDeviceTrace": CLS_ADMIN,
}


def classify(operation_id: str) -> str:
    """Unmapped operations land in the admin class — the most-shed
    bucket — but the completeness test keeps the map exhaustive so
    that default never actually decides anything."""
    return ROUTE_CLASSES.get(operation_id, CLS_ADMIN)


# ---------------------------------------------------------------------------
# budgets + token buckets
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ClassBudget:
    """Per-class admission budget: token-bucket rate + concurrency
    slots + how long an over-budget request may QUEUE for a slot
    before the deadline sheds it (queue-with-deadline)."""

    rate: float  # sustained requests/second
    burst: float  # bucket depth
    max_concurrent: int  # concurrency slots (pool workers it may hold)
    queue_deadline_s: float  # max wait for a slot before 503


# documented in COVERAGE.md's serving-budget table — change both.
# Rates are per-node REST budgets: generous enough that a healthy
# validator client or test suite never notices them, tight enough
# that a flood drains the cheap classes' buckets long before the
# duty class feels anything. Scenarios/benches pass tighter budgets
# explicitly to make the sheds observable at small scale.
DEFAULT_BUDGETS: dict[str, ClassBudget] = {
    CLS_DUTY: ClassBudget(1000.0, 400.0, 64, 5.0),
    CLS_CONSENSUS: ClassBudget(500.0, 200.0, 32, 1.0),
    CLS_LIGHT: ClassBudget(200.0, 100.0, 16, 0.5),
    CLS_ADMIN: ClassBudget(50.0, 25.0, 4, 0.25),
}


class TokenBucket:
    """Classic token bucket over an injectable monotonic clock."""

    def __init__(self, rate: float, burst: float, clock=None):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock or SYSTEM_CLOCK
        self.tokens = float(burst)
        self._t = self.clock.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> float:
        """0.0 = token granted; > 0 = refused, value is the seconds
        until `n` tokens will have refilled (the Retry-After hint)."""
        with self._lock:
            now = self.clock.monotonic()
            self.tokens = min(
                self.burst, self.tokens + (now - self._t) * self.rate
            )
            self._t = now
            if self.tokens >= n:
                self.tokens -= n
                return 0.0
            if self.rate <= 0:
                return 60.0
            return (n - self.tokens) / self.rate


# ---------------------------------------------------------------------------
# brownout ladder
# ---------------------------------------------------------------------------

# loop-lag thresholds (seconds) that trip each class's breaker; the
# cheapest class browns out first and duty NEVER does — the ladder
# exists to keep block import + duty scheduling responsive, so the
# duty class rides whatever lag remains after the sheds
DEFAULT_BROWNOUT_THRESHOLDS: dict[str, float] = {
    CLS_ADMIN: 0.05,
    CLS_LIGHT: 0.10,
    CLS_CONSENSUS: 0.25,
}


class BrownoutLadder:
    """Per-class circuit breakers driven by loop-lag samples.

    `sample(lag)` judges every class's breaker: lag at/over the class
    threshold is a failure, lag under half the threshold a success
    (the gap is hysteresis — mid-band samples leave the state alone).
    `allows(cls)` gates admission; an open breaker re-probes half-open
    after `reset_timeout` with a bounded probe budget per sample
    interval, so recovery is gradual, not a stampede.
    """

    def __init__(
        self,
        thresholds: dict[str, float] | None = None,
        clock=None,
        failure_threshold: int = 2,
        reset_timeout: float = 2.0,
        half_open_max: int = 4,
        on_transition=None,
    ):
        self.clock = clock or SYSTEM_CLOCK
        self.thresholds = dict(
            DEFAULT_BROWNOUT_THRESHOLDS
            if thresholds is None
            else thresholds
        )
        self.breakers = {
            cls: CircuitBreaker(
                name=f"brownout:{cls}",
                failure_threshold=failure_threshold,
                reset_timeout=reset_timeout,
                half_open_max=half_open_max,
                clock=self.clock,
                on_transition=on_transition,
            )
            for cls in self.thresholds
        }
        self.samples = 0
        self.last_lag = 0.0

    def sample(self, lag: float) -> None:
        """Feed one loop-lag observation to every class breaker."""
        self.samples += 1
        self.last_lag = lag
        for cls, thr in self.thresholds.items():
            b = self.breakers[cls]
            if lag >= thr:
                b.on_failure()
            elif lag <= thr * 0.5:
                b.on_success()

    def allows(self, cls: str) -> bool:
        b = self.breakers.get(cls)
        return True if b is None else b.allows()

    def state(self, cls: str) -> BreakerState:
        b = self.breakers.get(cls)
        return BreakerState.closed if b is None else b.state

    def active(self) -> bool:
        """Any class browned out right now? (The cache serves stale
        under brownout instead of refusing.)"""
        return any(
            b.state is not BreakerState.closed
            for b in self.breakers.values()
        )

    def retry_after(self, cls: str) -> float:
        """Seconds the refused client should back off: the remainder
        of the breaker's open window (floor 0.5 s)."""
        b = self.breakers.get(cls)
        if b is None or b.state is BreakerState.closed:
            return 0.5
        remaining = b.reset_timeout - (
            self.clock.monotonic() - b.opened_at
        )
        return max(0.5, remaining)

    def states_indexed(self) -> dict[str, int]:
        """{cls: 0|1|2} for the lodestar_api_brownout_state gauge."""
        return {
            cls: BREAKER_STATE_INDEX[b.state]
            for cls, b in self.breakers.items()
        }


class LoopLagProbe:
    """Measures asyncio scheduling lag: sleep(interval) and see how
    late the wakeup lands. The excess IS the time the loop spent on
    other work — block import, bridge hops — per tick. Feeds the
    ladder and (when attached) the lodestar_event_loop_lag_seconds
    histogram. Tests bypass `run` and call `ladder.sample` with a
    ManualClock directly."""

    def __init__(self, ladder: BrownoutLadder, interval: float = 0.25,
                 clock=None, histogram=None):
        self.ladder = ladder
        self.interval = interval
        self.clock = clock or SYSTEM_CLOCK
        self.histogram = histogram
        self.ticks = 0
        self._task = None

    async def run(self) -> None:
        import asyncio

        while True:
            t0 = self.clock.monotonic()
            await asyncio.sleep(self.interval)
            lag = max(
                0.0, self.clock.monotonic() - t0 - self.interval
            )
            self.ticks += 1
            self.ladder.sample(lag)
            if self.histogram is not None:
                self.histogram.observe(lag)

    def start(self, loop) -> None:
        self._task = loop.create_task(self.run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


# ---------------------------------------------------------------------------
# head-keyed response cache
# ---------------------------------------------------------------------------


@dataclass
class CacheEntry:
    generation: int
    head_root: str
    body: bytes  # serialized once, served many
    status: int
    headers: dict = field(default_factory=dict)


class ResponseCache:
    """Serialize-once cache for hot idempotent GET routes, keyed on
    the full request path+query and scoped to the chain generation.

    `attach(emitter)` registers an inline listener on the chain event
    bus: head / finalized_checkpoint / chain_reorg bump the
    generation, so a cached body is FRESH exactly while the head that
    produced it stands (head-root-keyed). Stale entries are kept for
    stale-while-revalidate service under brownout and age out by LRU.
    """

    INVALIDATING_TOPICS = ("head", "finalized_checkpoint", "chain_reorg")

    def __init__(self, max_entries: int = 1024):
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, CacheEntry] = OrderedDict()
        self.max_entries = max_entries
        self.generation = 0
        self.head_root = ""
        self.hits = 0
        self.misses = 0
        self.stale_hits = 0
        self.invalidations = 0

    def attach(self, emitter) -> None:
        emitter.add_listener(self.on_event)

    def on_event(self, topic: str, data) -> None:
        if topic not in self.INVALIDATING_TOPICS:
            return
        root = ""
        if isinstance(data, dict):
            root = str(data.get("block") or data.get("root") or "")
        self.invalidate(head_root=root)

    def invalidate(self, head_root: str = "") -> None:
        with self._lock:
            self.generation += 1
            if head_root:
                self.head_root = head_root
            self.invalidations += 1

    def lookup(self, key: str, allow_stale: bool = False):
        """Fresh CacheEntry, or a stale one when `allow_stale` (the
        brownout path), else None. Counts hit/miss/stale."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            if entry.generation == self.generation:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry
            if allow_stale:
                self._entries.move_to_end(key)
                self.stale_hits += 1
                return entry
            self.misses += 1
            return None

    def store(self, key: str, body: bytes, status: int = 200,
              headers: dict | None = None) -> None:
        with self._lock:
            self._entries[key] = CacheEntry(
                generation=self.generation,
                head_root=self.head_root,
                body=body,
                status=status,
                headers=dict(headers or {}),
            )
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {
                "hit": self.hits,
                "miss": self.misses,
                "stale": self.stale_hits,
            }

    def hit_ratio(self) -> float:
        with self._lock:
            served = self.hits + self.stale_hits
            total = served + self.misses
            return served / total if total else 0.0


# ---------------------------------------------------------------------------
# admission controller (the facade the server drives)
# ---------------------------------------------------------------------------


@dataclass
class Admission:
    """Outcome of try_admit: either a held concurrency slot (release()
    in a finally) or a refusal the server turns into 429/503 +
    Retry-After."""

    ok: bool
    cls: str
    status: int = 0
    reason: str = ""
    retry_after: float = 0.0
    _release: object = None

    def release(self) -> None:
        if self._release is not None:
            rel, self._release = self._release, None
            rel()


class ServingOverload:
    """The serving-tier fault domain in one object: classification,
    budgets, buckets, brownout ladder, response cache, and the shed /
    response / timeout ledgers the metrics + scenarios read.

    Thread model: `try_admit` / `note_*` are called from pool worker
    threads; the ladder is sampled from the loop's lag probe; the
    cache listener runs inline on `emit`. All ledgers are
    lock-guarded dict bumps, same discipline as DeviceExecutor's.
    """

    def __init__(
        self,
        budgets: dict[str, ClassBudget] | None = None,
        ladder: BrownoutLadder | None = None,
        cache: ResponseCache | None = None,
        clock=None,
        pool_workers: int = 16,
        pool_backlog: int = 32,
        max_body_bytes: int = 16 * 1024 * 1024,
        sse_max_subscribers: int = 8,
        bridge_timeout_s: float = 30.0,
    ):
        self.clock = clock or SYSTEM_CLOCK
        self.budgets = dict(DEFAULT_BUDGETS)
        if budgets:
            self.budgets.update(budgets)
        self.buckets = {
            cls: TokenBucket(b.rate, b.burst, clock=self.clock)
            for cls, b in self.budgets.items()
        }
        self._sems = {
            cls: threading.Semaphore(b.max_concurrent)
            for cls, b in self.budgets.items()
        }
        self.ladder = ladder if ladder is not None else BrownoutLadder(
            clock=self.clock
        )
        self.cache = cache if cache is not None else ResponseCache()
        self.pool_workers = pool_workers
        self.pool_backlog = pool_backlog
        self.max_body_bytes = max_body_bytes
        self.sse_max_subscribers = sse_max_subscribers
        self.bridge_timeout_s = bridge_timeout_s
        self._lock = threading.Lock()
        # ledgers (lodestar_api_* gauges sample these at scrape)
        self.sheds: dict[tuple[str, str], int] = {}
        self.admitted: dict[str, int] = {}
        self.inflight: dict[str, int] = {cls: 0 for cls in self.budgets}
        self.responses: dict[int, int] = {}  # status code -> count
        self.timeouts = 0  # bridge timeouts (504s)

    # -- classification ------------------------------------------------

    def classify(self, operation_id: str) -> str:
        return classify(operation_id)

    # -- ledgers -------------------------------------------------------

    def note_shed(self, cls: str, reason: str) -> None:
        with self._lock:
            key = (cls, reason)
            self.sheds[key] = self.sheds.get(key, 0) + 1

    def shed_counts(self) -> dict[tuple[str, str], int]:
        with self._lock:
            return dict(self.sheds)

    def note_response(self, status: int) -> None:
        with self._lock:
            self.responses[status] = self.responses.get(status, 0) + 1

    def response_counts(self) -> dict[int, int]:
        with self._lock:
            return dict(self.responses)

    def note_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def inflight_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self.inflight)

    # -- admission -----------------------------------------------------

    def try_admit(self, cls: str) -> Admission:
        """Brownout first (cheapest refusal), then the rate bucket,
        then a concurrency slot with the class's queue deadline."""
        budget = self.budgets.get(cls) or self.budgets[CLS_ADMIN]
        if not self.ladder.allows(cls):
            self.note_shed(cls, "brownout")
            return Admission(
                False, cls, 503, "brownout",
                retry_after=self.ladder.retry_after(cls),
            )
        wait = self.buckets[cls].take()
        if wait > 0:
            self.note_shed(cls, "rate_limited")
            return Admission(
                False, cls, 429, "rate_limited", retry_after=wait
            )
        sem = self._sems[cls]
        if not sem.acquire(timeout=budget.queue_deadline_s):
            self.note_shed(cls, "queue_deadline")
            return Admission(
                False, cls, 503, "queue_deadline",
                retry_after=max(0.5, budget.queue_deadline_s),
            )
        with self._lock:
            self.admitted[cls] = self.admitted.get(cls, 0) + 1
            self.inflight[cls] = self.inflight.get(cls, 0) + 1

        def _release():
            sem.release()
            with self._lock:
                self.inflight[cls] -= 1

        return Admission(True, cls, _release=_release)


# ---------------------------------------------------------------------------
# metrics bridge (node.py wiring; mirrors bind_executor_collectors)
# ---------------------------------------------------------------------------


def bind_api_collectors(metrics, overload: ServingOverload,
                        emitter=None) -> None:
    """Wire the m.api registry namespace (metrics/beacon.py) to sample
    the serving-tier ledgers at scrape time."""

    def _sheds(g):
        for (cls, reason), n in overload.shed_counts().items():
            g.set(n, cls=cls, reason=reason)

    metrics.sheds_total.add_collect(_sheds)
    metrics.inflight.add_collect(
        lambda g: [
            g.set(n, cls=c)
            for c, n in overload.inflight_counts().items()
        ]
    )
    metrics.brownout_state.add_collect(
        lambda g: [
            g.set(v, cls=c)
            for c, v in overload.ladder.states_indexed().items()
        ]
    )
    metrics.response_cache_total.add_collect(
        lambda g: [
            g.set(n, result=r)
            for r, n in overload.cache.counts().items()
        ]
    )
    metrics.request_timeouts_total.add_collect(
        lambda g: g.set(overload.timeouts)
    )
    if emitter is not None:
        metrics.sse_subscribers.add_collect(
            lambda g: g.set(emitter.subscriber_count())
        )
        metrics.sse_dropped_total.add_collect(
            lambda g: [
                g.set(n, topic=t) for t, n in emitter.dropped.items()
            ]
        )
        metrics.sse_evictions_total.add_collect(
            lambda g: g.set(emitter.evictions)
        )

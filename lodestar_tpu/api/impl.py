"""API business logic over the beacon chain.

Reference analog: beacon-node/src/api/impl/ — the per-namespace route
implementations (beacon, validator, node, config, debug). Each method
returns JSON-compatible data per the eth2 beacon-API spec shapes
(snake_case keys, numbers as strings, 0x-hex roots).
"""

from __future__ import annotations

from ..params import ForkSeq, preset
from ..utils.bits import bits_to_hex, hex_to_bits
from ..statetransition import util


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _cp(cp) -> dict:
    return {"epoch": str(int(cp.epoch)), "root": _hex(cp.root)}


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class BeaconApiImpl:
    """All namespaces in one impl bound to a chain (+ optional pools,
    node services)."""

    def __init__(self, cfg, types, chain, node=None, version="lodestar-tpu/r2"):
        self.cfg = cfg
        self.types = types
        self.chain = chain
        self.node = node
        self.version = version

    # -- beacon namespace ----------------------------------------------

    def get_genesis(self) -> dict:
        st = self.chain.get_state(self.chain.genesis_root).state
        return {
            "genesis_time": str(int(self.chain.genesis_time)),
            "genesis_validators_root": _hex(
                bytes(st.genesis_validators_root)
            ),
            "genesis_fork_version": _hex(self.cfg.GENESIS_FORK_VERSION),
        }

    def _resolve_state(self, state_id):
        state_id = str(state_id)  # numeric path params arrive as ints
        chain = self.chain
        if state_id == "head":
            return chain.head_state
        if state_id == "genesis":
            return chain.get_state(chain.genesis_root)
        if state_id == "finalized":
            v = chain.get_state(chain.finalized_checkpoint.root)
            if v is None:
                raise ApiError(404, "finalized state pruned")
            return v
        if state_id == "justified":
            v = chain.get_state(chain.justified_checkpoint.root)
            if v is None:
                raise ApiError(404, "justified state pruned")
            return v
        if state_id.startswith("0x"):
            for root, view in chain._states.items():
                if view.hash_tree_root(self.types) == bytes.fromhex(
                    state_id[2:]
                ):
                    return view
            raise ApiError(404, f"state {state_id} not found")
        # by slot
        try:
            slot = int(state_id)
        except ValueError:
            raise ApiError(400, f"invalid state id {state_id}") from None
        for root, view in self.chain._states.items():
            if int(view.state.slot) == slot:
                return view
        raise ApiError(404, f"state at slot {slot} not found")

    def get_state_fork(self, state_id: str) -> dict:
        st = self._resolve_state(state_id).state
        return {
            "previous_version": _hex(bytes(st.fork.previous_version)),
            "current_version": _hex(bytes(st.fork.current_version)),
            "epoch": str(int(st.fork.epoch)),
        }

    def get_state_finality_checkpoints(self, state_id: str) -> dict:
        st = self._resolve_state(state_id).state
        return {
            "previous_justified": _cp(st.previous_justified_checkpoint),
            "current_justified": _cp(st.current_justified_checkpoint),
            "finalized": _cp(st.finalized_checkpoint),
        }

    def get_state_validators(self, state_id: str) -> list:
        st = self._resolve_state(state_id).state
        epoch = util.get_current_epoch(st)
        out = []
        for i, (v, bal) in enumerate(zip(st.validators, st.balances)):
            out.append(
                {
                    "index": str(i),
                    "balance": str(int(bal)),
                    "status": _validator_status(v, epoch),
                    "validator": {
                        "pubkey": _hex(bytes(v.pubkey)),
                        "effective_balance": str(int(v.effective_balance)),
                        "slashed": bool(v.slashed),
                        "activation_epoch": str(int(v.activation_epoch)),
                        "exit_epoch": str(int(v.exit_epoch)),
                    },
                }
            )
        return out

    def get_state_root(self, state_id: str) -> dict:
        view = self._resolve_state(state_id)
        return {"root": _hex(view.hash_tree_root(self.types))}

    def get_state_validator_balances(self, state_id: str) -> list:
        """routes/beacon/state.ts getStateValidatorBalances."""
        st = self._resolve_state(state_id).state
        return [
            {"index": str(i), "balance": str(int(b))}
            for i, b in enumerate(st.balances)
        ]

    def get_epoch_committees(
        self, state_id: str, epoch: str = "", index: str = "", slot: str = ""
    ) -> list:
        """Committees for an epoch (routes/beacon/state.ts
        getEpochCommittees), filterable by index/slot."""
        st = self._resolve_state(state_id).state
        ep = int(epoch) if epoch else util.get_current_epoch(st)
        p = preset()
        sh = util.get_shuffling(st, ep)
        out = []
        for s in range(
            ep * p.SLOTS_PER_EPOCH, (ep + 1) * p.SLOTS_PER_EPOCH
        ):
            if slot and s != int(slot):
                continue
            for ci, committee in enumerate(sh.committees_at_slot(s)):
                if index and ci != int(index):
                    continue
                out.append(
                    {
                        "index": str(ci),
                        "slot": str(s),
                        "validators": [str(int(v)) for v in committee],
                    }
                )
        return out

    def _sync_committee_for_epoch(self, view, epoch: int | None):
        """current vs next sync committee by period, erroring outside
        the two-period window the state can answer for (the reference's
        getSyncCommitteeForEpoch semantics)."""
        per = preset().EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        st = view.state
        state_period = util.get_current_epoch(st) // per
        period = state_period if epoch is None else epoch // per
        if period == state_period:
            return st.current_sync_committee
        if period == state_period + 1:
            return st.next_sync_committee
        raise ApiError(
            400,
            f"epoch {epoch} outside the state's sync-committee "
            f"window (periods {state_period}..{state_period + 1})",
        )

    def get_epoch_sync_committees(
        self, state_id: str, epoch: str = ""
    ) -> dict:
        """Sync committee duty indices (routes/beacon/state.ts
        getEpochSyncCommittees)."""
        view = self._resolve_state(state_id)
        if view.fork_seq < ForkSeq.altair:
            raise ApiError(400, "sync committees require altair")
        st = view.state
        committee = self._sync_committee_for_epoch(
            view, int(epoch) if epoch else None
        )
        pubkey_to_index = {
            bytes(v.pubkey): i for i, v in enumerate(st.validators)
        }
        indices = []
        for pk in committee.pubkeys:
            vi = pubkey_to_index.get(bytes(pk))
            if vi is None:
                raise ApiError(
                    500,
                    "sync committee pubkey missing from the registry "
                    "— state/committee mismatch",
                )
            indices.append(str(vi))
        return {
            "validators": indices,
            "validator_aggregates": [indices],
        }

    def get_blob_sidecars(self, block_id: str) -> list:
        """Blob sidecars of a block (routes/beacon/blob.ts)."""
        from .json_codec import to_json

        root = self._resolve_block_root(block_id)
        if self.chain.db is None:
            raise ApiError(503, "no db")
        got = self.chain.db.blob_sidecars.get(root)
        if got is None:
            return []
        fork, sidecars = got
        ns = self.types.by_fork[fork]
        return [to_json(ns.BlobSidecar, sc) for sc in sidecars]

    def get_block_rewards(self, block_id: str) -> dict:
        """Proposer reward breakdown for a block
        (routes/beacon/rewards.ts getBlockRewards; chain/rewards/*).
        Computed as the proposer balance delta across the block's
        transition (covers attestation inclusion + sync aggregate
        rewards; slashing inclusion rewards fold in)."""
        root = self._resolve_block_root(block_id)
        got = self._block_with_fork_by_root(root)
        if got is None:
            raise ApiError(404, "block not found")
        fork, signed = got
        block = signed.message
        parent = self.chain.get_state(bytes(block.parent_root))
        if parent is None:
            raise ApiError(
                503, "parent state for reward computation not cached"
            )
        # Replay: advance the parent to the block's slot FIRST (epoch
        # processing must not pollute the delta at epoch boundaries),
        # then measure the proposer's balance across the block-only
        # transition.
        from ..chain.chain import _clone
        from ..statetransition import state_transition
        from ..statetransition.slot import process_slots

        work = _clone(parent, self.types)
        process_slots(
            self.cfg, work, int(block.slot), self.types
        )
        prop = int(block.proposer_index)
        pre_bal = int(work.state.balances[prop])
        state_transition(
            self.cfg,
            work,
            signed,
            self.types,
            verify_state_root=False,
            verify_proposer=False,
            verify_signatures=False,
        )
        total = int(work.state.balances[prop]) - pre_bal
        return {
            "proposer_index": str(prop),
            "total": str(total),
            "attestations": str(total),
            "sync_aggregate": "0",
            "proposer_slashings": "0",
            "attester_slashings": "0",
        }

    def _block_with_fork_by_root(self, root: bytes):
        blk = self.chain.get_block(root)
        if blk is not None:
            from ..statetransition.slot import fork_at_epoch

            fork = fork_at_epoch(
                self.cfg,
                int(blk.message.slot) // preset().SLOTS_PER_EPOCH,
            )
            return fork, blk
        if self.chain.db is not None:
            got = self.chain.db.block.get(root)
            if got is not None:
                return got
        return None

    def get_block_header(self, block_id: str) -> dict:
        root = self._resolve_block_root(block_id)
        node = self.chain.fork_choice.proto.get_node(root)
        if node is None:
            raise ApiError(404, f"block {block_id} not found")
        return {
            "root": _hex(root),
            "canonical": True,
            "header": {
                "message": {
                    "slot": str(node.slot),
                    "parent_root": _hex(node.parent_root or b"\x00" * 32),
                    "state_root": _hex(node.state_root),
                },
            },
        }

    def get_block_headers(self, slot: str = "", parent_root: str = "") -> list:
        """routes/beacon/block.ts getBlockHeaders: list headers filtered
        by slot and/or parent_root (the canonical chain view the proto
        array answers)."""
        proto = self.chain.fork_choice.proto
        head = self.chain.head_root
        want_slot = int(slot) if slot != "" else None
        want_parent = (
            bytes.fromhex(str(parent_root).removeprefix("0x"))
            if parent_root
            else None
        )
        if want_slot is None and want_parent is None:
            # unfiltered: the head header only (reference behavior)
            return [self.get_block_header("head")]
        out = []
        for node in proto.nodes:
            if node is None:
                continue
            if want_slot is not None and node.slot != want_slot:
                continue
            if (
                want_parent is not None
                and (node.parent_root or b"") != want_parent
            ):
                continue
            canonical = (
                proto.ancestor_at_slot(head, node.slot)
                == node.block_root
            )
            out.append(
                {
                    "root": _hex(node.block_root),
                    "canonical": canonical,
                    "header": {
                        "message": {
                            "slot": str(node.slot),
                            "parent_root": _hex(
                                node.parent_root or b"\x00" * 32
                            ),
                            "state_root": _hex(node.state_root),
                        },
                    },
                }
            )
        return out

    def get_deposit_snapshot(self) -> dict:
        """EIP-4881 deposit tree snapshot
        (routes/beacon/index.ts getDepositSnapshot)."""
        eth1 = getattr(self.chain, "eth1", None)
        if eth1 is None or len(eth1.tree) == 0:
            raise ApiError(404, "no deposit snapshot available")
        tree = eth1.tree
        count = len(tree)
        return {
            "finalized": [
                _hex(h) for h in tree.finalized_roots(count)
            ],
            "deposit_root": _hex(tree.root),
            "deposit_count": str(count),
            "execution_block_hash": _hex(
                getattr(eth1, "latest_block_hash", b"\x00" * 32) or b"\x00" * 32
            ),
            "execution_block_height": str(
                getattr(eth1, "latest_block_number", 0) or 0
            ),
        }

    # -- proof namespace (routes/proof.ts) -------------------------------

    def get_state_proof(self, state_id: str, field: str = "") -> dict:
        """SSZ Merkle proof of one top-level BeaconState field against
        the state root (proof.ts getStateProof; field-level descriptor
        subset — the ssz/proofs machinery provides the branches)."""
        from ..ssz.proofs import container_field_branch

        if not field:
            raise ApiError(400, "field query parameter required")
        view = self._resolve_state(state_id)
        state_t = self.types.by_fork[view.fork].BeaconState
        if field not in state_t.field_names:
            raise ApiError(400, f"unknown state field {field!r}")
        leaf, branch, idx = container_field_branch(
            state_t, view.state, field
        )
        depth = len(branch)
        return {
            "type": "single",
            "field": field,
            "gindex": str((1 << depth) + idx),
            "leaf": _hex(leaf),
            "witnesses": [_hex(w) for w in branch],
            "state_root": _hex(state_t.hash_tree_root(view.state)),
        }

    def get_block_proof(self, block_id: str, field: str = "") -> dict:
        """SSZ Merkle proof of one top-level BeaconBlock field against
        the block root (proof.ts getBlockProof subset)."""
        from ..ssz.proofs import container_field_branch

        if not field:
            raise ApiError(400, "field query parameter required")
        root = self._resolve_block_root(block_id)
        got = self._block_with_fork_by_root(root)
        if got is None:
            raise ApiError(404, f"block {block_id} not found")
        fork, signed = got
        block_t = self.types.by_fork[fork].BeaconBlock
        if field not in block_t.field_names:
            raise ApiError(400, f"unknown block field {field!r}")
        leaf, branch, idx = container_field_branch(
            block_t, signed.message, field
        )
        depth = len(branch)
        return {
            "type": "single",
            "field": field,
            "gindex": str((1 << depth) + idx),
            "leaf": _hex(leaf),
            "witnesses": [_hex(w) for w in branch],
            "block_root": _hex(root),
        }

    def _resolve_block_root(self, block_id) -> bytes:
        block_id = str(block_id)  # numeric path params arrive as ints
        chain = self.chain
        if block_id == "head":
            return chain.head_root
        if block_id == "genesis":
            return chain.genesis_root
        if block_id == "finalized":
            return chain.finalized_checkpoint.root
        if block_id.startswith("0x"):
            return bytes.fromhex(block_id[2:])
        try:
            slot = int(block_id)
        except ValueError:
            raise ApiError(400, f"invalid block id {block_id}") from None
        root = chain.fork_choice.proto.ancestor_at_slot(
            chain.head_root, slot
        )
        if root is None:
            raise ApiError(404, f"no block at slot {slot}")
        return root

    async def publish_block(self, signed_block) -> dict:
        await self.chain.process_block(signed_block)
        return {}

    def _block_with_fork(self, block_id: str):
        root = self._resolve_block_root(block_id)
        blk = self.chain.get_block(root)
        fork = None
        if blk is not None:
            from ..statetransition.slot import fork_at_epoch

            fork = fork_at_epoch(
                self.cfg,
                int(blk.message.slot) // preset().SLOTS_PER_EPOCH,
            )
        elif self.chain.db is not None:
            raw = self.chain.db.block.get_binary(root)
            if raw is not None:
                fork, blk = self.chain.db.block.decode_value(raw)
        if blk is None:
            raise ApiError(404, f"block {block_id} not found")
        return root, fork, blk

    def get_block_v2(self, block_id: str) -> dict:
        from .json_codec import to_json

        _, fork, blk = self._block_with_fork(block_id)
        t = self.types.by_fork[fork].SignedBeaconBlock
        # v2 responses carry the fork version at the top level
        return {
            "version": fork,
            "execution_optimistic": False,
            "data": to_json(t, blk),
        }

    def get_block_root(self, block_id: str) -> dict:
        return {"root": _hex(self._resolve_block_root(block_id))}

    async def publish_block_json(self, body: dict) -> dict:
        """POST /eth/v1/beacon/blocks with a JSON SignedBeaconBlock
        (fork inferred from the slot)."""
        from ..statetransition.slot import fork_at_epoch
        from .json_codec import from_json

        try:
            slot = int(body["message"]["slot"])
            fork = fork_at_epoch(
                self.cfg, slot // preset().SLOTS_PER_EPOCH
            )
            block = from_json(
                self.types.by_fork[fork].SignedBeaconBlock, body
            )
        except (KeyError, ValueError, TypeError) as e:
            raise ApiError(400, f"malformed block: {e}") from e
        await self.chain.process_block(block)
        return {}

    async def publish_blinded_block_json(self, body: dict) -> dict:
        """POST /eth/v{1,2}/beacon/blinded_blocks: the unblinding path
        (routes/beacon/block.ts publishBlindedBlock → chain unblinds via
        the builder, execution/builder/http.ts:60 submitBlindedBlock).
        The VC-signed blinded block goes to the relay, which reveals
        the full ExecutionPayload; the reconstructed full block must
        match the header commitment, then imports + publishes."""
        from ..statetransition.slot import fork_at_epoch
        from .json_codec import from_json, to_json  # noqa: F401

        builder = (
            getattr(self.node, "builder", None) if self.node else None
        )
        if builder is None:
            raise ApiError(503, "no builder configured to unblind")
        try:
            slot = int(body["message"]["slot"])
            fork = fork_at_epoch(
                self.cfg, slot // preset().SLOTS_PER_EPOCH
            )
            ns = self.types.by_fork[fork]
            signed_blinded = from_json(
                ns.SignedBlindedBeaconBlock, body
            )
        except (KeyError, ValueError, TypeError, AttributeError) as e:
            # AttributeError: pre-bellatrix forks have no blinded types
            raise ApiError(400, f"malformed blinded block: {e}") from e
        try:
            revealed = await builder.submit_blinded_block(
                fork, signed_blinded
            )
        except Exception as e:
            # a reveal failure is the worst builder fault: the slot is
            # likely lost — feed the fault-inspection-window breaker
            if hasattr(builder, "register_fault"):
                builder.register_fault(slot, kind="missed_slot")
            raise ApiError(502, f"relay reveal failed: {e}") from e
        # deneb+ reveals carry the blobs bundle alongside the payload
        payload, bundle = (
            revealed if isinstance(revealed, tuple) else (revealed, None)
        )
        # the revealed payload must hash to the committed header
        hdr = signed_blinded.message.body.execution_payload_header
        if bytes(payload.block_hash) != bytes(hdr.block_hash):
            # a mismatched reveal loses the slot just like a failed
            # one — it must feed the inspection window too
            if hasattr(builder, "register_fault"):
                builder.register_fault(slot, kind="missed_slot")
            raise ApiError(
                400, "revealed payload does not match bid header"
            )
        from ..execution.builder import unblind_signed_block

        full = unblind_signed_block(ns, signed_blinded, payload)
        sidecars = None
        comms = list(
            getattr(
                signed_blinded.message.body, "blob_kzg_commitments", []
            )
            or []
        )
        if comms:
            from ..chain.blobs import blob_sidecars_from_block

            bundle = bundle or {}
            sidecars = blob_sidecars_from_block(
                self.types,
                fork,
                full,
                list(bundle.get("blobs") or []),
                list(bundle.get("proofs") or []),
            )
        await self.chain.process_block(full, blob_sidecars=sidecars)
        if hasattr(builder, "register_success"):
            builder.register_success(slot)
        if self.node is not None and self.node.network is not None:
            await self.node.network.publish_block(fork, full)
        return {}

    # -- pool namespace ---------------------------------------------------

    def _pools(self):
        if self.node is None or self.node.op_pool is None:
            raise ApiError(503, "op pool not available")
        return self.node.op_pool

    async def submit_pool_attestations(self, body: list) -> dict:
        from .json_codec import from_json

        if self.node is None or self.node.att_pool is None:
            raise ApiError(503, "attestation pool not available")
        errors = []
        for i, obj in enumerate(body):
            try:
                att = from_json(self.types.Attestation, obj)
                self.node.att_pool.add(att)
                unagg = getattr(self.node, "unagg_pool", None)
                if unagg is not None:
                    unagg.add(att, len(att.aggregation_bits))
            except Exception as e:
                errors.append({"index": i, "message": repr(e)})
        if errors:
            raise ApiError(400, f"failures: {errors}")
        return {}

    def get_pool_attestations(self) -> list:
        from .json_codec import to_json

        if self.node is None or self.node.att_pool is None:
            raise ApiError(503, "attestation pool not available")
        st = self.chain.head_state.state
        atts = self.node.att_pool.get_attestations_for_block(
            int(st.slot) + 1
        )
        return [to_json(self.types.Attestation, a) for a in atts]

    def submit_pool_voluntary_exit(self, body: dict) -> dict:
        from .json_codec import from_json

        self._pools().add_voluntary_exit(
            from_json(self.types.SignedVoluntaryExit, body)
        )
        return {}

    def submit_pool_attester_slashing(self, body: dict) -> dict:
        from .json_codec import from_json

        self._pools().add_attester_slashing(
            from_json(self.types.AttesterSlashing, body)
        )
        return {}

    def submit_pool_proposer_slashing(self, body: dict) -> dict:
        from .json_codec import from_json

        self._pools().add_proposer_slashing(
            from_json(self.types.ProposerSlashing, body)
        )
        return {}

    # -- debug / light client ---------------------------------------------

    def get_state_v2(self, state_id: str) -> dict:
        """Full state download for checkpoint sync
        (debug.ts getStateV2). The reference serves raw SSZ under
        Accept: application/octet-stream; this JSON server carries the
        SSZ bytes hex-encoded (documented deviation — the client is
        sync/checkpoint.py)."""
        view = self._resolve_state(state_id)
        t = self.types.by_fork[view.fork].BeaconState
        return {
            "version": view.fork,
            "execution_optimistic": False,
            "data_ssz": t.serialize(view.state).hex(),
        }

    def get_debug_fork_choice(self) -> dict:
        """Proto-array dump (debug/fork_choice route)."""
        proto = self.chain.fork_choice.proto
        nodes = []
        for n in proto.nodes:
            if n is None:
                continue
            nodes.append(
                {
                    "slot": str(n.slot),
                    "block_root": _hex(n.block_root),
                    "parent_root": _hex(n.parent_root)
                    if n.parent_root
                    else None,
                    "justified_epoch": str(n.justified_epoch),
                    "finalized_epoch": str(n.finalized_epoch),
                    "weight": str(n.weight),
                    "execution_status": str(n.execution_status.name)
                    if n.execution_status is not None
                    else "pre_merge",
                }
            )
        return {
            "justified_checkpoint": _cp(self.chain.justified_checkpoint),
            "finalized_checkpoint": _cp(self.chain.finalized_checkpoint),
            "fork_choice_nodes": nodes,
        }

    def _lc_server(self):
        lc = self.chain.light_client_server
        if lc is None:
            raise ApiError(503, "light client server not enabled")
        return lc

    def get_light_client_bootstrap(self, block_root: str) -> dict:
        from .json_codec import to_json

        lc = self._lc_server()
        root = bytes.fromhex(block_root.removeprefix("0x"))
        boot = lc.get_bootstrap(root)
        if boot is None:
            raise ApiError(404, "no bootstrap for that root")
        return to_json(self.types.LightClientBootstrap, boot)

    def get_light_client_finality_update(self) -> dict:
        from .json_codec import to_json

        lc = self._lc_server()
        if lc.latest_finality_update is None:
            raise ApiError(404, "no finality update yet")
        return to_json(
            self.types.LightClientFinalityUpdate,
            lc.latest_finality_update,
        )

    def get_light_client_optimistic_update(self) -> dict:
        from .json_codec import to_json

        lc = self._lc_server()
        if lc.latest_optimistic_update is None:
            raise ApiError(404, "no optimistic update yet")
        return to_json(
            self.types.LightClientOptimisticUpdate,
            lc.latest_optimistic_update,
        )

    # -- validator production ---------------------------------------------

    def produce_attestation_data(
        self, slot: str, committee_index: str
    ) -> dict:
        from .json_codec import to_json

        data = self._attestation_data(int(slot), int(committee_index))
        return to_json(self.types.AttestationData, data)

    def _attestation_data(self, slot: int, committee_index: int):
        chain = self.chain
        st = chain.head_state.state
        epoch = slot // preset().SLOTS_PER_EPOCH
        data = self.types.AttestationData.default()
        data.slot = slot
        data.index = committee_index
        data.beacon_block_root = chain.head_root
        data.source = st.current_justified_checkpoint
        try:
            target_root = bytes(util.get_block_root(st, epoch))
        except ValueError:
            target_root = chain.head_root
        data.target.epoch = epoch
        data.target.root = target_root
        return data

    def produce_block_v2(
        self, slot: str, randao_reveal: str, graffiti: str = ""
    ) -> dict:
        from .json_codec import to_json

        slot_i = int(slot)
        pool = self._produce_pool_inputs(slot_i)
        block, post = self.chain.produce_block(
            slot_i,
            bytes.fromhex(randao_reveal.removeprefix("0x")),
            attestations=pool["atts"],
            sync_aggregate=pool["sync_aggregate"],
            graffiti=(
                bytes.fromhex(graffiti.removeprefix("0x")).ljust(32, b"\x00")
                if graffiti
                else b"\x00" * 32
            ),
        )
        t = self.types.by_fork[post.fork].BeaconBlock
        return {"version": post.fork, **{"data": to_json(t, block)}}

    def _builder_usable(self, builder, slot: int) -> bool:
        """Gate the builder race (reference: the proposal-time circuit
        breaker): operator kill-switch, the relay-error inspection
        window, and the chain's own recent missed slots all veto the
        race — a relay that wins bids and withholds payloads shows up
        as missed proposals, not client errors."""
        if not getattr(builder, "enabled", True):
            return False
        cb = getattr(builder, "circuit_breaker", None)
        if cb is None:
            return True
        if not builder.available(slot):
            return False
        from ..execution.builder import missed_slots_in_window

        try:
            missed = missed_slots_in_window(self.chain, slot, cb.window)
        except Exception:
            return True  # breaker must never veto on bookkeeping bugs
        return missed <= cb.allowed_faults

    async def produce_block_v3(
        self,
        slot: str,
        randao_reveal: str,
        graffiti: str = "",
        skip_randao_verification: str = "",
        builder_boost_factor: str = "",
    ) -> dict:
        """routes/validator.ts produceBlockV3 (api/impl/validator/
        index.ts:837): when a builder relay is wired, its getHeader bid
        RACES the engine's getPayload and the winner is chosen by
        bid_value * builder_boost_factor / 100 vs the engine's block
        value — a builder win returns a BLINDED block
        (Eth-Execution-Payload-Blinded: true) for the VC to sign and
        feed back through publish_blinded_block (the unblinding path).
        Pre-deneb `data` is the BeaconBlock; deneb+ full responses are
        BlockContents {block, kzg_proofs, blobs}; blinded responses are
        the blinded block alone (builder holds the blobs). The spec's
        envelope response headers ride the __headers__ convention
        (api/server.py emits + strips them)."""
        import asyncio as _asyncio

        from .json_codec import to_json

        if skip_randao_verification in ("1", "true", "True"):
            # spec: stub reveal, production must not verify it — this
            # node's production path never verifies the reveal against
            # the proposer key (the SIGNED block gets full validation
            # on import), so the flag is accepted as a no-op
            pass
        slot_i = int(slot)
        boost = (
            int(builder_boost_factor) if builder_boost_factor else 100
        )
        chain = self.chain
        builder = (
            getattr(self.node, "builder", None) if self.node else None
        )
        if builder is not None and not self._builder_usable(
            builder, slot_i
        ):
            builder = None

        # advance a scratch view once: proposer pubkey (builder bid
        # key), parent exec hash, and the engine's fcU attributes all
        # need the state AT the slot
        from ..chain.chain import _clone
        from ..statetransition.slot import process_slots

        work = _clone(chain.get_or_regen_state(chain.head_root), self.types)
        process_slots(self.cfg, work, slot_i, self.types)
        post_merge = work.fork_seq >= ForkSeq.bellatrix

        async def engine_side():
            if chain.execution_engine is None or not post_merge:
                return None, None, 0
            return await chain.prepare_execution_payload(slot_i, work)

        async def builder_side():
            if builder is None or boost == 0 or not post_merge:
                return None
            proposer = util.get_beacon_proposer_index(
                work.state, electra=work.fork_seq >= ForkSeq.electra
            )
            parent_hash = bytes(
                work.state.latest_execution_payload_header.block_hash
            )
            pubkey = bytes(work.state.validators[proposer].pubkey)
            try:
                bid = await builder.get_header(
                    slot_i, parent_hash, pubkey
                )
            except Exception:
                # relay fault -> local block wins; the fault feeds the
                # inspection-window breaker so repeated errors skip
                # the race on upcoming slots
                if hasattr(builder, "register_fault"):
                    builder.register_fault(slot_i)
                return None
            if bid is not None and hasattr(builder, "register_success"):
                builder.register_success(slot_i)
            return bid

        (engine_payload, bundle, engine_value), bid = await _asyncio.gather(
            engine_side(), builder_side()
        )
        use_builder = bid is not None and (
            engine_payload is None
            or bid.value * boost // 100 > engine_value
        )
        if (
            use_builder
            and work.fork_seq >= ForkSeq.deneb
            and getattr(bid, "blob_kzg_commitments", None) is None
        ):
            # deneb+: a bid without blob commitments cannot be trusted
            # to carry none — fall back to the local block rather than
            # sign a possibly-invalid commitment set (the reference
            # requires the bid's blinded blobs bundle)
            use_builder = False
            if engine_payload is None:
                raise ApiError(
                    503,
                    "builder bid lacks blob commitments and no local "
                    "payload is available",
                )

        pool = self._produce_pool_inputs(slot_i)
        common = dict(
            attestations=pool["atts"],
            sync_aggregate=pool["sync_aggregate"],
            graffiti=(
                bytes.fromhex(graffiti.removeprefix("0x")).ljust(32, b"\x00")
                if graffiti
                else b"\x00" * 32
            ),
        )
        reveal = bytes.fromhex(randao_reveal.removeprefix("0x"))
        if use_builder:
            block, post = chain.produce_block(
                slot_i,
                reveal,
                execution_payload_header=bid.header,
                blob_kzg_commitments=bid.blob_kzg_commitments,
                work=work,
                **common,
            )
            t = self.types.by_fork[post.fork].BlindedBeaconBlock
            val = str(bid.value)
            return {
                "version": post.fork,
                "data": to_json(t, block),
                "execution_payload_blinded": True,
                "execution_payload_value": val,
                "consensus_block_value": "0",
                "__headers__": {
                    "Eth-Consensus-Version": post.fork,
                    "Eth-Execution-Payload-Blinded": "true",
                    "Eth-Execution-Payload-Value": val,
                    "Eth-Consensus-Block-Value": "0",
                },
            }
        # blobs_bundle is a plain dict {commitments, proofs, blobs}
        # (execution/engine.py GetPayloadResponse)
        bundle = bundle or {}
        blobs = list(bundle.get("blobs") or [])
        block, post = chain.produce_block(
            slot_i,
            reveal,
            execution_payload=engine_payload,
            blobs=blobs or None,
            # reuse the engine's commitments — recomputing each blob's
            # KZG commitment host-side blows the proposal budget
            blob_kzg_commitments=list(bundle.get("commitments") or [])
            or None,
            work=work,
            **common,
        )
        t = self.types.by_fork[post.fork].BeaconBlock
        data = to_json(t, block)
        fork = post.fork
        if ForkSeq[fork] >= ForkSeq.deneb:
            data = {
                "block": data,
                "kzg_proofs": [
                    "0x" + bytes(p).hex()
                    for p in (bundle.get("proofs") or [])
                ],
                "blobs": ["0x" + bytes(b).hex() for b in blobs],
            }
        val = str(engine_value)
        return {
            "version": fork,
            "data": data,
            "execution_payload_blinded": False,
            "execution_payload_value": val,
            "consensus_block_value": "0",
            "__headers__": {
                "Eth-Consensus-Version": fork,
                "Eth-Execution-Payload-Blinded": "false",
                "Eth-Execution-Payload-Value": val,
                "Eth-Consensus-Block-Value": "0",
            },
        }

    def _produce_pool_inputs(self, slot_i: int) -> dict:
        """Op-pool harvest shared by produceBlockV2/V3."""
        atts = []
        sync_aggregate = None
        if self.node is not None:
            if self.node.att_pool is not None:
                atts = self.node.att_pool.get_attestations_for_block(
                    slot_i,
                    state=self.chain.head_state.state,
                )
            contrib = getattr(self.node, "contrib_pool", None)
            if (
                contrib is not None
                and self.chain.head_state.fork_seq >= ForkSeq.altair
            ):
                sync_aggregate = contrib.get_sync_aggregate(
                    slot_i - 1, self.chain.head_root
                )
        return {"atts": atts, "sync_aggregate": sync_aggregate}

    # -- node: identity / peers -------------------------------------------

    def get_identity(self) -> dict:
        net = getattr(self.node, "network", None) if self.node else None
        if net is None:
            return {"peer_id": "", "enr": "", "p2p_addresses": []}
        rec = net.discovery.record if net.discovery else None
        return {
            "peer_id": net.peer_id,
            "enr": rec.tag() if rec else "",
            "p2p_addresses": [
                f"/ip4/{net.host.host}/tcp/{net.host.port}"
            ],
            "discovery_addresses": [
                f"/ip4/{rec.host}/udp/{rec.udp_port}" if rec else ""
            ],
        }

    def get_peers(self) -> list:
        net = getattr(self.node, "network", None) if self.node else None
        if net is None:
            return []
        out = []
        for pid, conn in net.host.conns.items():
            score = net.peer_manager.scores.get(pid)
            out.append(
                {
                    "peer_id": pid,
                    "state": "connected",
                    "direction": "outbound"
                    if conn.outbound
                    else "inbound",
                    "score": score.value() if score else 0.0,
                }
            )
        return out

    def get_state_validator(self, state_id: str, validator_id: str) -> dict:
        """routes/beacon/state.ts getStateValidator: one validator by
        index or 0x-pubkey."""
        st = self._resolve_state(state_id).state
        vid = str(validator_id)
        if vid.startswith("0x"):
            pk = bytes.fromhex(vid[2:])
            idx = util.PubkeyIndexView(st).get(pk)
            if idx is None:
                raise ApiError(404, f"validator {vid} not found")
        else:
            try:
                idx = int(vid)
            except ValueError:
                raise ApiError(400, f"bad validator id {vid}") from None
            if idx < 0:
                raise ApiError(400, f"bad validator id {vid}")
            if idx >= len(st.validators):
                raise ApiError(404, f"validator {idx} not found")
        v = st.validators[idx]
        epoch = util.get_current_epoch(st)
        return {
            "index": str(idx),
            "balance": str(int(st.balances[idx])),
            "status": _validator_status(v, epoch),
            "validator": {
                "pubkey": _hex(bytes(v.pubkey)),
                "effective_balance": str(int(v.effective_balance)),
                "slashed": bool(v.slashed),
                "activation_epoch": str(int(v.activation_epoch)),
                "exit_epoch": str(int(v.exit_epoch)),
            },
        }

    def get_state_randao(self, state_id: str, epoch: str = "") -> dict:
        """routes/beacon/state.ts getStateRandao."""
        st = self._resolve_state(state_id).state
        ep = int(epoch) if epoch else util.get_current_epoch(st)
        cur = util.get_current_epoch(st)
        p = preset()
        if not (
            cur - p.EPOCHS_PER_HISTORICAL_VECTOR + 1 <= ep <= cur
        ):
            raise ApiError(400, f"epoch {ep} outside randao window")
        return {"randao": _hex(bytes(util.get_randao_mix(st, ep)))}

    def get_block_attestations(self, block_id: str) -> list:
        """routes/beacon/block.ts getBlockAttestations."""
        from .json_codec import to_json

        root = self._resolve_block_root(block_id)
        got = self._block_with_fork_by_root(root)
        if got is None:
            raise ApiError(404, f"block {block_id} not found")
        _fork, signed = got
        return [
            to_json(self.types.Attestation, att)
            for att in signed.message.body.attestations
        ]

    def _op_pool_list(self, attr: str, type_name: str) -> list:
        from .json_codec import to_json

        pool = getattr(self.node, "op_pool", None) if self.node else None
        if pool is None:
            return []
        t = getattr(self.types, type_name)
        ops = getattr(pool, attr, [])
        if isinstance(ops, dict):  # index-keyed pools store op values
            ops = ops.values()
        return [to_json(t, v) for v in ops]

    def get_pool_attester_slashings(self) -> list:
        return self._op_pool_list(
            "attester_slashings", "AttesterSlashing"
        )

    def get_pool_proposer_slashings(self) -> list:
        return self._op_pool_list(
            "proposer_slashings", "ProposerSlashing"
        )

    def get_pool_voluntary_exits(self) -> list:
        return self._op_pool_list(
            "voluntary_exits", "SignedVoluntaryExit"
        )

    def get_pool_bls_changes(self) -> list:
        return self._op_pool_list(
            "bls_changes", "SignedBLSToExecutionChange"
        )

    def get_peer_count(self) -> dict:
        net = getattr(self.node, "network", None) if self.node else None
        conns = net.host.conns.values() if net else ()
        inbound = sum(1 for c in conns if not c.outbound)
        outbound = sum(1 for c in conns if c.outbound)
        return {
            "disconnected": "0",
            "connecting": "0",
            "connected": str(inbound + outbound),
            "disconnecting": "0",
        }

    def get_attestations_rewards(self, epoch: int, body=None) -> dict:
        """routes/beacon/rewards.ts getAttestationsRewards: per-flag
        attestation reward components for `epoch`'s PREVIOUS-epoch
        participation, computed from a state in epoch+1 with the same
        vectorized math the epoch transition uses (altair+ only)."""
        import numpy as np

        from ..statetransition.epoch import (
            EpochTransitionCache,
            _participation_arrays,
            _unslashed_participating,
        )
        from ..params import (
            PARTICIPATION_FLAG_WEIGHTS,
            TIMELY_HEAD_FLAG_INDEX,
            TIMELY_SOURCE_FLAG_INDEX,
            TIMELY_TARGET_FLAG_INDEX,
            WEIGHT_DENOMINATOR,
        )

        epoch = int(epoch)
        view = None
        for root, v in self.chain._states.items():
            if util.get_current_epoch(v.state) == epoch + 1:
                view = v
                break
        if view is None:
            # the head state works when it sits in epoch+1
            head = self.chain.head_state
            if util.get_current_epoch(head.state) == epoch + 1:
                view = head
        if view is None:
            raise ApiError(
                404,
                f"no cached state in epoch {epoch + 1} to derive "
                f"epoch-{epoch} attestation rewards from",
            )
        if view.fork_seq < ForkSeq.altair:
            raise ApiError(400, "attestation rewards require altair")
        st = view.state
        cache = EpochTransitionCache(self.cfg, st, view.fork_seq)
        p = preset()
        eb = cache.reg.effective_balance
        increments = eb // p.EFFECTIVE_BALANCE_INCREMENT
        base_reward_per_increment = (
            p.EFFECTIVE_BALANCE_INCREMENT
            * p.BASE_REWARD_FACTOR
            // util.integer_squareroot(cache.total_active_balance)
        )
        base_reward = increments * base_reward_per_increment
        active_increments = (
            cache.total_active_balance // p.EFFECTIVE_BALANCE_INCREMENT
        )
        prev_part, _ = _participation_arrays(st)
        n = cache.n
        el = cache.eligible
        comp = {}
        names = {
            TIMELY_SOURCE_FLAG_INDEX: "source",
            TIMELY_TARGET_FLAG_INDEX: "target",
            TIMELY_HEAD_FLAG_INDEX: "head",
        }
        for flag_index, weight in enumerate(
            PARTICIPATION_FLAG_WEIGHTS
        ):
            mask = _unslashed_participating(
                cache, prev_part, flag_index
            )
            participating_increments = int(increments[mask].sum())
            vals = np.zeros(n, np.int64)
            if not cache.is_in_inactivity_leak:
                reward = (
                    base_reward
                    * weight
                    * participating_increments
                    // (active_increments * WEIGHT_DENOMINATOR)
                )
                vals = np.where(el & mask, reward, 0)
            if flag_index != TIMELY_HEAD_FLAG_INDEX:
                vals = vals - np.where(
                    el & ~mask,
                    base_reward * weight // WEIGHT_DENOMINATOR,
                    0,
                )
            comp[names[flag_index]] = vals
        want = None
        if body:
            want = {int(x) for x in body}
        total = []
        for i in range(n):
            if not el[i]:
                continue
            if want is not None and i not in want:
                continue
            total.append(
                {
                    "validator_index": str(i),
                    "head": str(int(comp["head"][i])),
                    "target": str(int(comp["target"][i])),
                    "source": str(int(comp["source"][i])),
                    "inclusion_delay": "0",
                    "inactivity": "0",
                }
            )
        return {"ideal_rewards": [], "total_rewards": total}

    def get_sync_committee_rewards(self, block_id: str, body=None) -> dict:
        """routes/beacon/rewards.ts getSyncCommitteeRewards: per-
        participant reward for a block's SyncAggregate."""
        root = self._resolve_block_root(block_id)
        got = self._block_with_fork_by_root(root)
        if got is None:
            raise ApiError(404, f"block {block_id} not found")
        _fork, signed = got
        block = signed.message
        view = self.chain.get_state(bytes(block.parent_root))
        if view is None:
            raise ApiError(503, "parent state not cached")
        if view.fork_seq < ForkSeq.altair:
            raise ApiError(400, "sync rewards require altair")
        st = view.state
        p = preset()
        total_active = sum(
            v.effective_balance
            for v in st.validators
            if util.is_active_validator(
                v, util.get_current_epoch(st)
            )
        )
        total_base = (
            p.EFFECTIVE_BALANCE_INCREMENT
            * p.BASE_REWARD_FACTOR
            * (total_active // p.EFFECTIVE_BALANCE_INCREMENT)
            // util.integer_squareroot(total_active)
        )
        from ..params import SYNC_REWARD_WEIGHT, WEIGHT_DENOMINATOR

        max_reward = (
            total_base
            * SYNC_REWARD_WEIGHT
            // WEIGHT_DENOMINATOR
            // p.SLOTS_PER_EPOCH
        )
        participant_reward = max_reward // p.SYNC_COMMITTEE_SIZE
        pk2i = util.PubkeyIndexView(st)
        want = {int(x) for x in body} if body else None
        out = []
        agg = block.body.sync_aggregate
        for pk, bit in zip(
            st.current_sync_committee.pubkeys,
            agg.sync_committee_bits,
        ):
            idx = pk2i.get(bytes(pk))
            if idx is None or (want is not None and idx not in want):
                continue
            out.append(
                {
                    "validator_index": str(idx),
                    "reward": str(
                        participant_reward if bit else -participant_reward
                    ),
                }
            )
        return out

    # -- lodestar admin namespace (routes/lodestar.ts) -------------------

    async def write_profile(self, duration: str = "1") -> dict:
        """Admin-triggered CPU profile of the chain's event loop
        (lodestar.ts writeProfile): cProfile enabled ON the loop
        thread for `duration` seconds; returns the top entries."""
        import asyncio
        import cProfile
        import io
        import pstats

        secs = min(30.0, max(0.1, float(duration)))
        pr = cProfile.Profile()
        pr.enable()
        await asyncio.sleep(secs)
        pr.disable()
        buf = io.StringIO()
        pstats.Stats(pr, stream=buf).sort_stats("cumulative").print_stats(
            40
        )
        return {"duration": secs, "profile": buf.getvalue()}

    def write_heapdump(self) -> dict:
        """Heap snapshot via tracemalloc (lodestar.ts writeHeapdump
        analog). First call starts tracing and returns a baseline;
        later calls return the current top allocations."""
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return {"status": "tracing started; call again for a snapshot"}
        snap = tracemalloc.take_snapshot()
        top = snap.statistics("lineno")[:40]
        return {
            "total_kib": sum(s.size for s in top) // 1024,
            "top": [str(s) for s in top],
        }

    async def device_trace(self, duration_ms: str = "") -> dict:
        """Admin-triggered jax.profiler capture (the device-layer
        sibling of write_profile): runs the profiler for the requested
        window — bounded by the node's --device-trace-max-ms knob, one
        capture at a time — and returns the trace directory for
        offline inspection (TensorBoard / xprof). The sleep runs in an
        executor so the chain's event loop keeps serving."""
        import asyncio
        import functools

        from ..metrics import device as device_telemetry

        max_ms = (
            getattr(self.node, "device_trace_max_ms", 5000.0)
            if self.node is not None
            else 5000.0
        )
        try:
            ms = float(duration_ms) if duration_ms else 100.0
        except ValueError:
            raise ApiError(
                400, f"bad duration_ms {duration_ms!r}"
            ) from None
        ms = min(float(max_ms), max(1.0, ms))
        out_dir = (
            getattr(self.node, "device_trace_dir", None)
            if self.node is not None
            else None
        )
        try:
            result = await asyncio.get_running_loop().run_in_executor(
                None,
                functools.partial(
                    device_telemetry.profiler_capture, ms, out_dir
                ),
            )
        except device_telemetry.CaptureBusyError as e:
            raise ApiError(409, str(e)) from None
        return result

    def get_gossip_queue_items(self) -> list:
        proc = getattr(self.node, "processor", None) if self.node else None
        if proc is None:
            return []
        q = proc.att_queue
        return [
            {
                "topic": "beacon_attestation",
                "length": len(q),
                "key_count": q.key_count,
                "dropped_total": q.dropped_total,
                "in_flight": proc._in_flight,
            }
        ]

    def get_state_cache_items(self) -> list:
        return [
            {
                "root": _hex(root),
                "slot": str(int(view.state.slot)),
                "fork": view.fork,
            }
            for root, view in self.chain._states.items()
        ]

    def get_gossip_peer_score_stats(self) -> list:
        net = getattr(self.node, "network", None) if self.node else None
        if net is None:
            return []
        return [
            {
                "peer_id": pid,
                "score": sc.value,
                "first_deliveries": sc.first_deliveries,
                "invalid": sc.invalid,
                "behaviour": sc.behaviour,
            }
            for pid, sc in net.gossip.scores.items()
        ]

    def get_block_import_traces(self) -> list:
        """Recent slow block-import traces from the tracer's ring
        buffer (metrics/tracing.py): per-stage durations for every
        pipeline stage of each slow slot, newest last. The debug
        surface for 'why was slot N slow' — the histogram bridge has
        the aggregates, this has the exemplars."""
        tracer = getattr(self.chain, "tracer", None)
        if tracer is None:
            return []
        return [
            {
                "slot": str(t["slot"]),
                "block_root": t["block_root"],
                "total_ms": t["total_ms"],
                "stages": t["stages"],
                "error": t["error"],
                "timestamp": t["timestamp"],
            }
            for t in tracer.buffer.snapshot()
        ]

    def get_sync_chains_debug_state(self) -> list:
        rs = getattr(self.node, "range_sync", None) if self.node else None
        if rs is None:
            return []
        return [
            {
                "status": str(getattr(rs, "state", "")),
                "peers": len(getattr(rs, "peers", ())),
                "batches": len(getattr(rs, "_batches", ())),
            }
        ]

    def get_peer(self, peer_id: str) -> dict:
        """routes/node.ts getPeer: one peer's detail."""
        net = getattr(self.node, "network", None) if self.node else None
        if net is None:
            raise ApiError(404, "no network")
        conn = net.host.conns.get(str(peer_id))
        if conn is None:
            raise ApiError(404, f"peer {peer_id} not connected")
        score = net.peer_manager.scores.get(str(peer_id))
        return {
            "peer_id": str(peer_id),
            "enr": "",
            "last_seen_p2p_address": (
                f"/ip4/{net.host.host}/tcp/"
                f"{conn.hello.get('tcp_port', 0)}"
            ),
            "state": "connected",
            "direction": "outbound" if conn.outbound else "inbound",
            "score": score.value() if score else 0.0,
        }

    # -- validator namespace --------------------------------------------

    def get_proposer_duties(self, epoch: int) -> list:
        """Per-slot proposers for an epoch, computed on the head state
        (api/impl/validator getProposerDuties)."""
        from ..params import DOMAIN_BEACON_PROPOSER

        p = preset()
        view = self.chain.head_state
        st = view.state
        head_epoch = util.get_current_epoch(st)
        if epoch not in (head_epoch, head_epoch + 1):
            raise ApiError(
                400, f"epoch {epoch} not current or next ({head_epoch})"
            )
        electra = view.fork_seq >= ForkSeq.electra
        indices = util.get_active_validator_indices(st, epoch)
        duties = []
        for s in range(
            epoch * p.SLOTS_PER_EPOCH, (epoch + 1) * p.SLOTS_PER_EPOCH
        ):
            seed = util.hash32(
                util.get_seed(st, epoch, DOMAIN_BEACON_PROPOSER)
                + util.uint_to_bytes8(s)
            )
            idx = util.compute_proposer_index(
                st, indices, seed, electra=electra
            )
            duties.append(
                {
                    "pubkey": _hex(bytes(st.validators[idx].pubkey)),
                    "validator_index": str(idx),
                    "slot": str(s),
                }
            )
        return duties

    def get_attester_duties(self, epoch: int, indices: list[int]) -> list:
        st = self.chain.head_state.state
        sh = util.get_shuffling(st, epoch)
        p = preset()
        wanted = set(indices)
        duties = []
        for slot in range(
            epoch * p.SLOTS_PER_EPOCH, (epoch + 1) * p.SLOTS_PER_EPOCH
        ):
            for ci, committee in enumerate(sh.committees_at_slot(slot)):
                for pos, v in enumerate(committee):
                    if int(v) in wanted:
                        duties.append(
                            {
                                "pubkey": _hex(
                                    bytes(st.validators[int(v)].pubkey)
                                ),
                                "validator_index": str(int(v)),
                                "committee_index": str(ci),
                                "committee_length": str(len(committee)),
                                "validator_committee_index": str(pos),
                                "slot": str(slot),
                            }
                        )
        return duties

    # -- validator namespace: aggregation ---------------------------------

    def _unagg_pool(self):
        pool = getattr(self.node, "unagg_pool", None) if self.node else None
        if pool is None:
            raise ApiError(503, "unaggregated pool not available")
        return pool

    def get_aggregated_attestation(
        self, slot: str = "", attestation_data_root: str = ""
    ) -> dict:
        """Best aggregate for (slot, data_root)
        (routes/validator.ts getAggregatedAttestation)."""
        from .json_codec import to_json

        agg = self._unagg_pool().get_aggregate(
            int(slot),
            bytes.fromhex(attestation_data_root.removeprefix("0x")),
        )
        if agg is None:
            raise ApiError(404, "no attestations for that data root")
        return to_json(self.types.Attestation, agg)

    async def publish_aggregate_and_proofs(self, body: list) -> dict:
        """SignedAggregateAndProof submissions
        (routes/validator.ts publishAggregateAndProofs): each aggregate
        runs the FULL gossip aggregate validation (three signature sets
        through the TPU verifier, processor.process_aggregate) before
        pooling/re-publish; invalid ones 400 (gossipHandlers
        submitPoolAggregateAndProofs semantics). Without a wired
        processor (embedded test api), falls back to direct pooling."""
        from ..chain.validation import GossipAction
        from .json_codec import from_json

        proc = getattr(self.node, "processor", None) if self.node else None
        has_validator = (
            proc is not None and proc.aggregate_validator is not None
        )
        errors = []
        for i, obj in enumerate(body):
            try:
                sap = from_json(
                    self.types.SignedAggregateAndProof, obj
                )
                if has_validator:
                    action = await proc.process_aggregate(sap)
                    if action == GossipAction.REJECT:
                        errors.append(
                            {"index": i, "message": "rejected: invalid"}
                        )
                        continue
                    if action != GossipAction.ACCEPT:
                        # IGNORE covers both duplicates and verifier
                        # overload — neither may reach the mesh
                        # unvalidated; duplicates were already forwarded
                        # when first accepted
                        continue
                elif self.node is not None and self.node.att_pool is not None:
                    self.node.att_pool.add(sap.message.aggregate)
                if self.node is not None and self.node.network is not None:
                    await self.node.network.publish_aggregate(sap)
            except Exception as e:
                errors.append({"index": i, "message": repr(e)})
        if errors:
            raise ApiError(400, f"failures: {errors}")
        return {}

    def prepare_beacon_committee_subnet(self, body: list) -> dict:
        """beacon_committee_subscriptions: drive attnet duty windows
        (routes/validator.ts prepareBeaconCommitteeSubnet)."""
        net = self.node.network if self.node else None
        for sub in body:
            subnet = int(sub.get("committee_index", 0)) % 64
            if net is not None:
                net.subscribe_att_subnet(subnet)
        return {}

    def prepare_sync_committee_subnets(self, body: list) -> dict:
        return {}

    def register_validator(self, body: list) -> dict:
        """Builder registrations (routes/validator.ts
        registerValidator): forwarded to the external builder when one
        is attached."""
        builder = getattr(self.node, "builder", None) if self.node else None
        if builder is not None and hasattr(
            builder, "register_validators"
        ):
            builder.register_validators(body)
        return {}

    def prepare_beacon_proposer(self, body: list) -> dict:
        """Fee-recipient preparations (routes/validator.ts
        prepareBeaconProposer)."""
        if self.node is not None:
            prep = getattr(self.node, "proposer_preparations", None)
            if prep is None:
                prep = {}
                self.node.proposer_preparations = prep
            for entry in body:
                prep[int(entry["validator_index"])] = entry[
                    "fee_recipient"
                ]
        return {}

    def get_liveness(self, epoch: str, body: list) -> list:
        """Per-validator liveness from the gossip seen-attester cache
        (routes/validator.ts getLiveness)."""
        av = (
            getattr(self.node, "attestation_validator", None)
            if self.node
            else None
        )
        seen = av.seen_attesters if av is not None else None
        ep = int(epoch)
        out = []
        for idx in body:
            i = int(idx)
            live = bool(seen is not None and seen.is_known(ep, i))
            out.append({"index": str(i), "is_live": live})
        return out

    # -- validator namespace: sync committee ------------------------------

    def get_sync_committee_duties(
        self, epoch: str, body: list
    ) -> list:
        """routes/validator.ts getSyncCommitteeDuties. Honors the
        epoch's sync-committee period (current or next)."""
        view = self.chain.head_state
        if view.fork_seq < ForkSeq.altair:
            return []
        st = view.state
        committee = self._sync_committee_for_epoch(view, int(epoch))
        wanted = {int(i) for i in body}
        pubkey_to_index = {
            bytes(v.pubkey): i for i, v in enumerate(st.validators)
        }
        duties: dict[int, list[int]] = {}
        for pos, pk in enumerate(committee.pubkeys):
            vi = pubkey_to_index.get(bytes(pk))
            if vi is not None and vi in wanted:
                duties.setdefault(vi, []).append(pos)
        return [
            {
                "pubkey": _hex(bytes(st.validators[vi].pubkey)),
                "validator_index": str(vi),
                "validator_sync_committee_indices": [
                    str(p) for p in positions
                ],
            }
            for vi, positions in duties.items()
        ]

    def _sync_pools(self):
        pool = (
            getattr(self.node, "sync_msg_pool", None)
            if self.node
            else None
        )
        contrib = (
            getattr(self.node, "contrib_pool", None)
            if self.node
            else None
        )
        if pool is None or contrib is None:
            raise ApiError(503, "sync committee pools not available")
        return pool, contrib

    def submit_pool_sync_committee_signatures(self, body: list) -> dict:
        """routes/beacon/pool.ts submitPoolSyncCommitteeSignatures."""
        from ..params import SYNC_COMMITTEE_SUBNET_COUNT

        pool, _ = self._sync_pools()
        view = self.chain.head_state
        st = view.state
        p = preset()
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        # committee by the MESSAGE slot's period (epoch(slot+1) rule,
        # mirroring get_sync_committee_duties) — near a period boundary
        # next-period messages would get wrong/missing positions from
        # current_sync_committee alone (ADVICE r3)
        pos_memo: dict[int, dict[bytes, list[int]]] = {}

        def positions_for(slot: int) -> dict[bytes, list[int]]:
            epoch = util.compute_epoch_at_slot(slot + 1)
            per = preset().EPOCHS_PER_SYNC_COMMITTEE_PERIOD
            period = epoch // per
            if period not in pos_memo:
                committee = self._sync_committee_for_epoch(view, epoch)
                m: dict[bytes, list[int]] = {}
                for pos, pk in enumerate(committee.pubkeys):
                    m.setdefault(bytes(pk), []).append(pos)
                pos_memo[period] = m
            return pos_memo[period]

        errors = []
        for i, msg in enumerate(body):
            try:
                vi = int(msg["validator_index"])
                pubkey_to_positions = positions_for(int(msg["slot"]))
                pk = bytes(st.validators[vi].pubkey)
                positions = pubkey_to_positions.get(pk, [])
                for pos in positions:
                    pool.add(
                        int(msg["slot"]),
                        bytes.fromhex(
                            msg["beacon_block_root"].removeprefix("0x")
                        ),
                        pos // sub_size,
                        pos % sub_size,
                        bytes.fromhex(
                            msg["signature"].removeprefix("0x")
                        ),
                    )
            except Exception as e:
                errors.append({"index": i, "message": repr(e)})
        if errors:
            raise ApiError(400, f"failures: {errors}")
        return {}

    def produce_sync_committee_contribution(
        self, slot: str = "", subcommittee_index: str = "",
        beacon_block_root: str = "",
    ) -> dict:
        pool, _ = self._sync_pools()
        c = pool.get_contribution(
            int(slot),
            bytes.fromhex(beacon_block_root.removeprefix("0x")),
            int(subcommittee_index),
        )
        if c is None:
            raise ApiError(404, "no contribution available")
        return {
            "slot": str(c["slot"]),
            "beacon_block_root": _hex(c["beacon_block_root"]),
            "subcommittee_index": str(c["subcommittee_index"]),
            "aggregation_bits": "0x"
            + bits_to_hex(c["aggregation_bits"]),
            "signature": _hex(c["signature"]),
        }

    def publish_contribution_and_proofs(self, body: list) -> dict:
        """routes/validator.ts publishContributionAndProofs."""
        _, contrib = self._sync_pools()
        errors = []
        for i, obj in enumerate(body):
            try:
                c = obj["message"]["contribution"]
                from ..params import SYNC_COMMITTEE_SUBNET_COUNT

                sub_size = (
                    preset().SYNC_COMMITTEE_SIZE
                    // SYNC_COMMITTEE_SUBNET_COUNT
                )
                contrib.add(
                    {
                        "slot": int(c["slot"]),
                        "beacon_block_root": bytes.fromhex(
                            c["beacon_block_root"].removeprefix("0x")
                        ),
                        "subcommittee_index": int(
                            c["subcommittee_index"]
                        ),
                        "aggregation_bits": hex_to_bits(
                            c["aggregation_bits"], sub_size
                        ),
                        "signature": bytes.fromhex(
                            c["signature"].removeprefix("0x")
                        ),
                    }
                )
            except Exception as e:
                errors.append({"index": i, "message": repr(e)})
        if errors:
            raise ApiError(400, f"failures: {errors}")
        return {}

    def submit_pool_bls_changes(self, body: list) -> dict:
        from .json_codec import from_json

        for obj in body:
            self._pools().add_bls_change(
                from_json(self.types.SignedBLSToExecutionChange, obj)
            )
        return {}

    def get_fork_schedule(self) -> list:
        from ..config.fork_config import ChainForkConfig

        return [
            {
                "previous_version": _hex(f.prev_version),
                "current_version": _hex(f.version),
                "epoch": str(f.epoch),
            }
            for f in ChainForkConfig(self.cfg).fork_schedule
        ]

    def get_deposit_contract(self) -> dict:
        return {
            "chain_id": str(self.cfg.DEPOSIT_CHAIN_ID),
            "address": _hex(self.cfg.DEPOSIT_CONTRACT_ADDRESS),
        }

    # -- node namespace --------------------------------------------------

    def get_health(self) -> int:
        return 200

    def get_version(self) -> dict:
        return {"version": self.version}

    def get_syncing(self) -> dict:
        head = self.chain.fork_choice.proto.get_node(self.chain.head_root)
        return {
            "head_slot": str(head.slot if head else 0),
            "sync_distance": "0",
            "is_syncing": False,
            "is_optimistic": False,
            "el_offline": True,
        }

    # -- config namespace -------------------------------------------------

    def get_spec(self) -> dict:
        p = preset()
        return {
            "SECONDS_PER_SLOT": str(self.cfg.SECONDS_PER_SLOT),
            "SLOTS_PER_EPOCH": str(p.SLOTS_PER_EPOCH),
            "ALTAIR_FORK_EPOCH": str(self.cfg.ALTAIR_FORK_EPOCH),
            "BELLATRIX_FORK_EPOCH": str(self.cfg.BELLATRIX_FORK_EPOCH),
            "CAPELLA_FORK_EPOCH": str(self.cfg.CAPELLA_FORK_EPOCH),
            "DENEB_FORK_EPOCH": str(self.cfg.DENEB_FORK_EPOCH),
            "ELECTRA_FORK_EPOCH": str(self.cfg.ELECTRA_FORK_EPOCH),
            "MAX_COMMITTEES_PER_SLOT": str(p.MAX_COMMITTEES_PER_SLOT),
            "TARGET_COMMITTEE_SIZE": str(p.TARGET_COMMITTEE_SIZE),
        }


def _validator_status(v, epoch: int) -> str:
    from ..params import FAR_FUTURE_EPOCH

    if int(v.activation_epoch) > epoch:
        return "pending_queued"
    if int(v.exit_epoch) == FAR_FUTURE_EPOCH:
        return "active_ongoing"
    if epoch < int(v.exit_epoch):
        return "active_exiting"
    return "exited_slashed" if v.slashed else "exited_unslashed"

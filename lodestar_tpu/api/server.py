"""Beacon REST API HTTP server.

Reference analog: BeaconRestApiServer on fastify
(beacon-node/src/api/rest/index.ts:38). stdlib HTTP server in a
daemon thread; async impl methods are bridged onto the node's asyncio
loop with run_coroutine_threadsafe (the fastify->chain boundary in the
reference is the same thread-hop, worker bridge §1).

Serving fault domain (ISSUE 20, api/overload.py): connections are
handled by a BOUNDED worker pool (over-backlog connections get a raw
503 + Retry-After instead of an unbounded thread), every matched
route passes per-class admission control (token bucket + concurrency
budget + brownout ladder), hot idempotent GETs are served from the
head-keyed response cache (stale under brownout), the async bridge
CANCELS the loop-side task on timeout (504), and SSE rides the
broadcast emitter's pre-serialized frames behind a subscriber cap.
"""

from __future__ import annotations

import asyncio
import inspect
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, HTTPServer

from .impl import ApiError, BeaconApiImpl
from .overload import EVENTSTREAM_OP, CLS_CONN, ServingOverload
from .routes import match_route


class _PooledHTTPServer(HTTPServer):
    """Bounded worker pool replacing ThreadingHTTPServer's
    thread-per-connection model: accepted connections are handed to a
    fixed pool, and once `pool_workers + pool_backlog` connections are
    in flight the listener refuses with a raw 503 + Retry-After on
    the socket — an accounted shed, never an unbounded thread."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, handler_cls, overload: ServingOverload):
        super().__init__(addr, handler_cls)
        self.overload = overload
        self._pool = ThreadPoolExecutor(
            max_workers=overload.pool_workers,
            thread_name_prefix="api-worker",
        )
        self._pending = 0
        self._plock = threading.Lock()

    def process_request(self, request, client_address):
        with self._plock:
            over = self._pending >= (
                self.overload.pool_workers + self.overload.pool_backlog
            )
            if not over:
                self._pending += 1
        if over:
            self.overload.note_shed(CLS_CONN, "pool_backlog")
            self.overload.note_response(503)
            try:
                request.sendall(
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    b"Retry-After: 1\r\n"
                    b"Content-Length: 0\r\n"
                    b"Connection: close\r\n\r\n"
                )
            except OSError:
                pass
            self.shutdown_request(request)
            return
        self._pool.submit(self._work, request, client_address)

    def _work(self, request, client_address):
        # mirrors ThreadingMixIn.process_request_thread
        try:
            self.finish_request(request, client_address)
        except Exception:
            self.handle_error(request, client_address)
        finally:
            self.shutdown_request(request)
            with self._plock:
                self._pending -= 1

    def handle_error(self, request, client_address):
        pass  # disconnects mid-response are the client's business

    def server_close(self):
        super().server_close()
        self._pool.shutdown(wait=False)


class BeaconRestApiServer:
    def __init__(
        self,
        impl: BeaconApiImpl,
        host: str = "127.0.0.1",
        port: int = 9596,
        loop: asyncio.AbstractEventLoop | None = None,
        overload: ServingOverload | None = None,
        metrics=None,  # the m.api namespace (metrics/beacon.py)
    ):
        self.impl = impl
        self.host = host
        self.port = port
        self.loop = loop
        self.overload = overload if overload is not None else ServingOverload()
        self.metrics = metrics
        self._httpd: _PooledHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._closing = False

    def start(self) -> int:
        impl = self.impl
        server = self
        ov = self.overload

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # idle keep-alive connections release their pool worker
            timeout = 10

            def _run(self):
                from urllib.parse import parse_qs

                path, _, qs = self.path.partition("?")
                query = parse_qs(qs)
                if self.command == "GET" and path == "/eth/v1/events":
                    self._sse(query)
                    return
                m = match_route(self.command, path)
                if m is None:
                    self._json(404, {"code": 404, "message": "route not found"})
                    return
                route, params = m
                cls = ov.classify(route.operation_id)
                t0 = time.monotonic()
                if server.metrics is not None:
                    server.metrics.requests_total.inc(
                        operation=route.operation_id
                    )
                # hot idempotent GETs: a FRESH cached body costs no
                # admission and no loop hop — that is the whole point
                # of the cache under a read flood
                cache_key = None
                if route.cacheable and self.command == "GET":
                    cache_key = self.path
                    entry = ov.cache.lookup(cache_key)
                    if entry is not None:
                        self._cached(route, entry, "hit", t0)
                        return
                adm = ov.try_admit(cls)
                if not adm.ok:
                    # stale-while-revalidate: under brownout/refusal a
                    # cacheable route serves its last good body
                    # instead of an error
                    if cache_key is not None:
                        entry = ov.cache.lookup(
                            cache_key, allow_stale=True
                        )
                        if entry is not None:
                            self._cached(route, entry, "stale", t0)
                            return
                    self._refused(route, adm)
                    return
                try:
                    self._handle(route, params, query, cache_key, t0)
                finally:
                    adm.release()

            def _handle(self, route, params, query, cache_key, t0):
                try:
                    body = None
                    if self.command == "POST":
                        n = int(self.headers.get("Content-Length") or 0)
                        if n > ov.max_body_bytes:
                            # refuse before reading: drop the
                            # connection rather than drain the body
                            self.close_connection = True
                            self._json(
                                413,
                                {
                                    "code": 413,
                                    "message": (
                                        f"body {n} over limit "
                                        f"{ov.max_body_bytes}"
                                    ),
                                },
                            )
                            return
                        raw = self.rfile.read(n) if n else b""
                        body = json.loads(raw) if raw else None
                    args = list(params.values())
                    # numeric path params (epoch) arrive as strings
                    args = [
                        int(a) if a.isdigit() else a for a in args
                    ]
                    for qp in route.query_params:
                        vals = query.get(qp)
                        args.append(vals[0] if vals else "")
                    if body is not None:
                        if route.raw_body:
                            args.append(body)
                        else:
                            args.append(
                                [int(x) for x in body]
                                if isinstance(body, list)
                                else body
                            )
                    fn = getattr(impl, route.impl_name)
                    result = fn(*args)
                    if inspect.iscoroutine(result):
                        if server.loop is None:
                            raise ApiError(500, "no loop for async route")
                        fut = asyncio.run_coroutine_threadsafe(
                            result, server.loop
                        )
                        try:
                            result = fut.result(
                                timeout=ov.bridge_timeout_s
                            )
                        except _FutureTimeout:
                            # cancel the loop-side task: an abandoned
                            # coroutine must not keep piling work onto
                            # the loop after its client gave up
                            fut.cancel()
                            ov.note_timeout()
                            self._json(
                                504,
                                {
                                    "code": 504,
                                    "message": "bridge timeout",
                                },
                                operation=route.operation_id,
                            )
                            return
                except ApiError as e:
                    self._json(
                        e.status,
                        {"code": e.status, "message": e.message},
                        operation=route.operation_id,
                    )
                    return
                except (ValueError, TypeError, KeyError) as e:
                    # malformed params/bodies are the client's fault
                    self._json(
                        400,
                        {"code": 400, "message": repr(e)},
                        operation=route.operation_id,
                    )
                    return
                except Exception as e:
                    self._json(
                        500,
                        {"code": 500, "message": repr(e)},
                        operation=route.operation_id,
                    )
                    return
                if server.metrics is not None:
                    server.metrics.response_time.observe(
                        time.monotonic() - t0,
                        operation=route.operation_id,
                    )
                if not route.wrap_data:
                    if isinstance(result, int):  # health: status only
                        ov.note_response(result)
                        self.send_response(result)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    self._json(200, result, cache_key=cache_key)
                    return
                self._json(200, {"data": result}, cache_key=cache_key)

            def _cached(self, route, entry, state, t0) -> None:
                """Serve a pre-serialized cache entry (hit or stale)."""
                ov.note_response(entry.status)
                if server.metrics is not None:
                    server.metrics.response_time.observe(
                        time.monotonic() - t0,
                        operation=route.operation_id,
                    )
                self.send_response(entry.status)
                self.send_header("Content-Type", "application/json")
                for k, v in entry.headers.items():
                    self.send_header(k, v)
                self.send_header("Lodestar-Cache", state)
                self.send_header(
                    "Content-Length", str(len(entry.body))
                )
                self.end_headers()
                self.wfile.write(entry.body)

            def _refused(self, route, adm) -> None:
                """429/503 + Retry-After for an admission refusal."""
                retry = max(1, int(adm.retry_after + 0.999))
                self._json(
                    adm.status,
                    {
                        "code": adm.status,
                        "message": (
                            f"{adm.reason} ({adm.cls} class)"
                        ),
                    },
                    headers={"Retry-After": str(retry)},
                    operation=route.operation_id,
                )

            def _sse(self, query) -> None:
                """Server-sent events stream (api/impl/events; topics
                via ?topics=head,block&topics=...). Frames arrive from
                the broadcast emitter pre-serialized; a subscriber that
                stops draining is evicted by the emitter and the
                stream ends at its next tick."""
                import queue as _queue

                from ..chain.events import TOPICS

                topics = []
                for entry in query.get("topics", []):
                    topics += [t for t in entry.split(",") if t]
                if not topics:
                    self._json(
                        400, {"code": 400, "message": "topics required"}
                    )
                    return
                unknown = [t for t in topics if t not in TOPICS]
                if unknown:
                    self._json(
                        400,
                        {
                            "code": 400,
                            "message": f"unknown topics: {unknown}",
                        },
                    )
                    return
                emitter = getattr(impl.chain, "events", None)
                if emitter is None:
                    self._json(
                        503, {"code": 503, "message": "events unavailable"}
                    )
                    return
                cls = ov.classify(EVENTSTREAM_OP)
                wait = ov.buckets[cls].take()
                if wait > 0:
                    ov.note_shed(cls, "rate_limited")
                    self._json(
                        429,
                        {"code": 429, "message": "rate_limited"},
                        headers={
                            "Retry-After": str(max(1, int(wait + 0.999)))
                        },
                    )
                    return
                sub = None
                if emitter.subscriber_count() < ov.sse_max_subscribers:
                    sub = emitter.subscribe(topics)
                if sub is None:
                    # server-side cap or the emitter's own cap: the
                    # stream is refused, not queued
                    ov.note_shed(cls, "sse_subscriber_cap")
                    self._json(
                        503,
                        {
                            "code": 503,
                            "message": "subscriber cap reached",
                        },
                        headers={"Retry-After": "5"},
                    )
                    return
                ov.note_response(200)
                try:
                    # the stream has no Content-Length: close the
                    # connection when it ends or a keep-alive client
                    # wedges waiting for the unterminated body
                    self.close_connection = True
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/event-stream"
                    )
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    while not server._closing:
                        if sub.evicted:
                            # emitter dropped us as a slow consumer
                            self.wfile.write(b": evicted\n\n")
                            self.wfile.flush()
                            break
                        try:
                            frame = sub.q.get(timeout=1.0)
                        except _queue.Empty:
                            # keep-alive comment frame
                            self.wfile.write(b":\n\n")
                            self.wfile.flush()
                            continue
                        self.wfile.write(frame)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    emitter.unsubscribe(sub)

            def _json(
                self, status: int, obj, headers=None,
                cache_key=None, operation=None,
            ) -> None:
                # impl methods attach spec response headers (e.g.
                # produceBlockV3's Eth-Execution-Payload-Blinded) via
                # a "__headers__" key, stripped before serializing
                if isinstance(obj, dict) and "__headers__" in obj:
                    headers = {
                        **(headers or {}),
                        **obj.pop("__headers__"),
                    }
                data = json.dumps(obj).encode()
                ov.note_response(status)
                if status >= 400 and operation is not None \
                        and server.metrics is not None:
                    server.metrics.errors_total.inc(
                        operation=operation
                    )
                if cache_key is not None and status == 200:
                    # serialize-once: the bytes just built are what
                    # every cache hit serves until the head moves
                    ov.cache.store(
                        cache_key, data, status, headers
                    )
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._run()

            def do_POST(self):
                self._run()

            def log_message(self, *a):
                pass

        self._httpd = _PooledHTTPServer(
            (self.host, self.port), Handler, ov
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._closing = True  # ends SSE streams at their next tick
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

"""Beacon REST API HTTP server.

Reference analog: BeaconRestApiServer on fastify
(beacon-node/src/api/rest/index.ts:38). stdlib ThreadingHTTPServer in a
daemon thread; async impl methods are bridged onto the node's asyncio
loop with run_coroutine_threadsafe (the fastify->chain boundary in the
reference is the same thread-hop, worker bridge §1).
"""

from __future__ import annotations

import asyncio
import inspect
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .impl import ApiError, BeaconApiImpl
from .routes import match_route


class BeaconRestApiServer:
    def __init__(
        self,
        impl: BeaconApiImpl,
        host: str = "127.0.0.1",
        port: int = 9596,
        loop: asyncio.AbstractEventLoop | None = None,
    ):
        self.impl = impl
        self.host = host
        self.port = port
        self.loop = loop
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._closing = False

    def start(self) -> int:
        impl = self.impl
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _run(self):
                from urllib.parse import parse_qs

                path, _, qs = self.path.partition("?")
                query = parse_qs(qs)
                if self.command == "GET" and path == "/eth/v1/events":
                    self._sse(query)
                    return
                m = match_route(self.command, path)
                if m is None:
                    self._json(404, {"code": 404, "message": "route not found"})
                    return
                route, params = m
                body = None
                if self.command == "POST":
                    n = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(n) if n else b""
                    body = json.loads(raw) if raw else None
                try:
                    args = list(params.values())
                    # numeric path params (epoch) arrive as strings
                    args = [
                        int(a) if a.isdigit() else a for a in args
                    ]
                    for qp in route.query_params:
                        vals = query.get(qp)
                        args.append(vals[0] if vals else "")
                    if body is not None:
                        if route.raw_body:
                            args.append(body)
                        else:
                            args.append(
                                [int(x) for x in body]
                                if isinstance(body, list)
                                else body
                            )
                    fn = getattr(impl, route.impl_name)
                    result = fn(*args)
                    if inspect.iscoroutine(result):
                        if server.loop is None:
                            raise ApiError(500, "no loop for async route")
                        result = asyncio.run_coroutine_threadsafe(
                            result, server.loop
                        ).result(timeout=30)
                except ApiError as e:
                    self._json(
                        e.status, {"code": e.status, "message": e.message}
                    )
                    return
                except (ValueError, TypeError, KeyError) as e:
                    # malformed params/bodies are the client's fault
                    self._json(400, {"code": 400, "message": repr(e)})
                    return
                except Exception as e:
                    self._json(500, {"code": 500, "message": repr(e)})
                    return
                if not route.wrap_data:
                    if isinstance(result, int):  # health: status only
                        self.send_response(result)
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    self._json(200, result)
                    return
                self._json(200, {"data": result})

            def _sse(self, query) -> None:
                """Server-sent events stream (api/impl/events; topics
                via ?topics=head,block&topics=...)."""
                import queue as _queue

                from ..chain.events import TOPICS

                topics = []
                for entry in query.get("topics", []):
                    topics += [t for t in entry.split(",") if t]
                if not topics:
                    self._json(
                        400, {"code": 400, "message": "topics required"}
                    )
                    return
                unknown = [t for t in topics if t not in TOPICS]
                if unknown:
                    self._json(
                        400,
                        {
                            "code": 400,
                            "message": f"unknown topics: {unknown}",
                        },
                    )
                    return
                emitter = getattr(impl.chain, "events", None)
                if emitter is None:
                    self._json(
                        503, {"code": 503, "message": "events unavailable"}
                    )
                    return
                q = emitter.subscribe(topics)
                try:
                    # the stream has no Content-Length: close the
                    # connection when it ends or a keep-alive client
                    # wedges waiting for the unterminated body
                    self.close_connection = True
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/event-stream"
                    )
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    while not server._closing:
                        try:
                            topic, data = q.get(timeout=1.0)
                        except _queue.Empty:
                            # keep-alive comment frame
                            self.wfile.write(b":\n\n")
                            self.wfile.flush()
                            continue
                        frame = (
                            f"event: {topic}\n"
                            f"data: {json.dumps(data)}\n\n"
                        ).encode()
                        self.wfile.write(frame)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    emitter.unsubscribe(q)

            def _json(self, status: int, obj, headers=None) -> None:
                # impl methods attach spec response headers (e.g.
                # produceBlockV3's Eth-Execution-Payload-Blinded) via
                # a "__headers__" key, stripped before serializing
                if isinstance(obj, dict) and "__headers__" in obj:
                    headers = {
                        **(headers or {}),
                        **obj.pop("__headers__"),
                    }
                data = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._run()

            def do_POST(self):
                self._run()

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._closing = True  # ends SSE streams at their next tick
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

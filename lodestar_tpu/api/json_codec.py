"""Generic SSZ value <-> beacon-API JSON codec.

Reference analog: the per-type toJson/fromJson codecs @chainsafe/ssz
attaches to every type (used by every REST route). Conventions follow
the beacon-api spec: uints as decimal strings, byte blobs as 0x-hex,
bitfields as 0x-hex of their SSZ encoding, containers as snake_case
objects.
"""

from __future__ import annotations

from ..ssz.basic import BooleanType, UintType
from ..ssz.composite import (
    BitlistType,
    BitvectorType,
    ByteListType,
    ByteVectorType,
    ContainerType,
    ListType,
    VectorType,
)


def to_json(t, v):
    if isinstance(t, UintType):
        return str(int(v))
    if isinstance(t, BooleanType):
        return bool(v)
    if isinstance(t, (ByteVectorType, ByteListType)):
        return "0x" + bytes(v).hex()
    if isinstance(t, (BitvectorType, BitlistType)):
        return "0x" + t.serialize(v).hex()
    if isinstance(t, (ListType, VectorType)):
        return [to_json(t.element_type, e) for e in v]
    if isinstance(t, ContainerType):
        return {
            name: to_json(ft, getattr(v, name)) for name, ft in t.fields
        }
    raise TypeError(f"no JSON codec for {t!r}")


def from_json(t, obj):
    if isinstance(t, UintType):
        return int(obj)
    if isinstance(t, BooleanType):
        return bool(obj)
    if isinstance(t, (ByteVectorType, ByteListType)):
        return bytes.fromhex(str(obj).removeprefix("0x"))
    if isinstance(t, (BitvectorType, BitlistType)):
        return t.deserialize(bytes.fromhex(str(obj).removeprefix("0x")))
    if isinstance(t, (ListType, VectorType)):
        return [from_json(t.element_type, e) for e in obj]
    if isinstance(t, ContainerType):
        missing = [name for name, _ in t.fields if name not in obj]
        if missing:
            # silent defaults would mask malformed bodies (typos,
            # dropped signatures) until deep in the state transition
            raise KeyError(
                f"{t.name} JSON missing fields: {', '.join(missing)}"
            )
        return t(
            **{name: from_json(ft, obj[name]) for name, ft in t.fields}
        )
    raise TypeError(f"no JSON codec for {t!r}")

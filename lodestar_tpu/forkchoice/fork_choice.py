"""ForkChoice: spec get_head over a ProtoArray, with LMD-GHOST votes,
proposer boost, unrealized justification, and checkpoint management.

Reference analog: packages/fork-choice/src/forkChoice/forkChoice.ts:80
(onBlock/onAttestation/updateHead), store.ts:52, computeDeltas.ts.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..params import GENESIS_EPOCH, preset


@dataclass
class Checkpoint:
    epoch: int
    root: bytes


@dataclass
class VoteTracker:
    current_root: bytes | None = None
    next_root: bytes | None = None
    next_epoch: int = 0


class ForkChoiceError(Exception):
    pass


def compute_deltas(
    indices: dict[bytes, int],
    n_nodes: int,
    votes: dict[int, VoteTracker],
    old_balances: list[int],
    new_balances: list[int],
    equivocating: set[int],
) -> list[int]:
    """Per-node weight changes from vote movement since last run
    (fork-choice/src/protoArray/computeDeltas.ts)."""
    deltas = [0] * n_nodes
    for i, vote in votes.items():
        if vote.current_root is None and vote.next_root is None:
            continue
        old_b = old_balances[i] if i < len(old_balances) else 0
        new_b = new_balances[i] if i < len(new_balances) else 0
        if i in equivocating:
            new_b = 0
            vote.next_root = None
        if vote.current_root is not None:
            idx = indices.get(vote.current_root)
            if idx is not None:
                deltas[idx] -= old_b
        if vote.next_root is not None:
            idx = indices.get(vote.next_root)
            if idx is not None:
                deltas[idx] += new_b
        vote.current_root = vote.next_root
    return deltas


class ForkChoice:
    """Host-side fork choice; pure bookkeeping, no crypto (signature
    validity is the verifier pool's job upstream)."""

    def __init__(
        self,
        cfg,
        proto_array,
        finalized_checkpoint: Checkpoint,
        justified_checkpoint: Checkpoint,
        justified_balances: list[int],
        current_slot: int = 0,
    ):
        from .proto_array import ProtoArray

        self.cfg = cfg
        self.proto: ProtoArray = proto_array
        self.finalized_checkpoint = finalized_checkpoint
        self.metrics = None  # lodestar_forkchoice_* family (node wiring)
        self.justified_checkpoint = justified_checkpoint
        self.unrealized_justified = justified_checkpoint
        self.unrealized_finalized = finalized_checkpoint
        self.justified_balances = list(justified_balances)
        self._old_balances = list(justified_balances)
        self.votes: dict[int, VoteTracker] = {}
        self.equivocating: set[int] = set()
        self.proposer_boost_root: bytes | None = None
        self._applied_boost: tuple[bytes, int] | None = None
        self.current_slot = current_slot
        self.head: bytes | None = None

    # -- time ----------------------------------------------------------

    def on_tick(self, slot: int) -> None:
        p = preset()
        prev = self.current_slot
        self.current_slot = slot
        if slot > prev and slot // p.SLOTS_PER_EPOCH > prev // p.SLOTS_PER_EPOCH:
            # crossed an epoch boundary (possibly several slots late):
            # pull up unrealized checkpoints (spec on_tick_per_slot)
            self._update_checkpoints(
                self.unrealized_justified, self.unrealized_finalized
            )
        if slot > prev:
            self.proposer_boost_root = None

    def _update_checkpoints(
        self, justified: Checkpoint, finalized: Checkpoint
    ) -> None:
        if justified.epoch > self.justified_checkpoint.epoch:
            self.justified_checkpoint = justified
        if finalized.epoch > self.finalized_checkpoint.epoch:
            self.finalized_checkpoint = finalized

    # -- block import ----------------------------------------------------

    def on_block(
        self,
        *,
        slot: int,
        block_root: bytes,
        parent_root: bytes,
        state_root: bytes,
        target_root: bytes,
        justified_checkpoint: Checkpoint,
        finalized_checkpoint: Checkpoint,
        unrealized_justified: Checkpoint | None = None,
        unrealized_finalized: Checkpoint | None = None,
        execution_block_hash: bytes | None = None,
        execution_status=None,
        is_timely: bool = False,
    ) -> None:
        """Register an imported block (chain verified it already)."""
        from .proto_array import ExecutionStatus, ProtoNode

        uj = unrealized_justified or justified_checkpoint
        uf = unrealized_finalized or finalized_checkpoint
        if execution_status is None:
            execution_status = (
                ExecutionStatus.syncing
                if execution_block_hash
                else ExecutionStatus.pre_merge
            )
        self.proto.on_block(
            ProtoNode(
                slot=slot,
                block_root=block_root,
                parent_root=parent_root,
                state_root=state_root,
                target_root=target_root,
                justified_epoch=justified_checkpoint.epoch,
                finalized_epoch=finalized_checkpoint.epoch,
                unrealized_justified_epoch=uj.epoch,
                unrealized_finalized_epoch=uf.epoch,
                execution_status=execution_status,
                execution_block_hash=execution_block_hash,
            )
        )
        # spec: current-epoch blocks update the store's checkpoints with
        # their realized values; unrealized values pull up at the next
        # epoch tick
        self._update_checkpoints(justified_checkpoint, finalized_checkpoint)
        if uj.epoch > self.unrealized_justified.epoch:
            self.unrealized_justified = uj
        if uf.epoch > self.unrealized_finalized.epoch:
            self.unrealized_finalized = uf
        # proposer boost for timely first block of the slot
        if is_timely and self.proposer_boost_root is None:
            self.proposer_boost_root = block_root

    # -- attestations ----------------------------------------------------

    def on_attestation(
        self,
        validator_indices,
        beacon_block_root: bytes,
        target_epoch: int,
    ) -> None:
        """Record LMD votes (already validated upstream: signature,
        slot windows, known block)."""
        for i in validator_indices:
            i = int(i)
            if i in self.equivocating:
                continue
            vote = self.votes.setdefault(i, VoteTracker())
            if (
                vote.next_root is None
                or target_epoch > vote.next_epoch
            ):
                vote.next_root = beacon_block_root
                vote.next_epoch = target_epoch

    def on_attester_slashing(self, indices) -> None:
        self.equivocating.update(int(i) for i in indices)

    # -- balances --------------------------------------------------------

    def set_justified_balances(self, balances: list[int]) -> None:
        self.justified_balances = list(balances)

    # -- head ------------------------------------------------------------

    def update_head(self) -> bytes:
        """Spec get_head via proto-array delta pass."""
        p = preset()
        deltas = compute_deltas(
            self.proto.indices,
            len(self.proto.nodes),
            self.votes,
            self._old_balances,
            self.justified_balances,
            self.equivocating,
        )
        # proposer boost: remove previous boost, add current
        if self._applied_boost is not None:
            root, amount = self._applied_boost
            idx = self.proto.indices.get(root)
            if idx is not None:
                deltas[idx] -= amount
            self._applied_boost = None
        if self.proposer_boost_root is not None:
            total = sum(self.justified_balances)
            committee_weight = total // p.SLOTS_PER_EPOCH
            boost = committee_weight * self.cfg.PROPOSER_SCORE_BOOST // 100
            idx = self.proto.indices.get(self.proposer_boost_root)
            if idx is not None:
                deltas[idx] += boost
                self._applied_boost = (self.proposer_boost_root, boost)
        self._old_balances = list(self.justified_balances)
        self.proto.apply_score_changes(
            deltas,
            self.justified_checkpoint.epoch,
            self.finalized_checkpoint.epoch,
            finalized_root=self.finalized_checkpoint.root,
            current_slot=self.current_slot,
        )
        old_head = self.head
        self.head = self.proto.find_head(
            self.justified_checkpoint.root, current_slot=self.current_slot
        )
        if self.metrics is not None:
            self.metrics.find_head_total.inc()
            if (
                old_head is not None
                and self.head != old_head
                and not self.proto.is_descendant(old_head, self.head)
            ):
                # common ancestor depth for the reorg label
                depth = 0
                anc = old_head
                while anc is not None and not self.proto.is_descendant(
                    anc, self.head
                ):
                    n = self.proto.get_node(anc)
                    if n is None or n.parent_root is None:
                        break
                    anc = n.parent_root
                    depth += 1
                self.metrics.reorg_total.inc(depth=str(depth))
        return self.head

    # -- queries ---------------------------------------------------------

    def has_block(self, root: bytes) -> bool:
        return root in self.proto.indices

    def is_descendant_of_finalized(self, root: bytes) -> bool:
        return self.proto.is_descendant(
            self.finalized_checkpoint.root, root
        )

    def prune(self) -> list:
        return self.proto.prune(self.finalized_checkpoint.root)

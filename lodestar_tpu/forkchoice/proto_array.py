"""ProtoArray: array-backed block DAG with best-descendant propagation.

Reference analog: packages/fork-choice/src/protoArray/protoArray.ts:15
and computeDeltas.ts — the proto-array fork-choice optimization: nodes
stored parent-before-child in a flat list, weights aggregated in one
backward pass, head lookup O(1) via bestDescendant pointers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum


class ExecutionStatus(str, Enum):
    valid = "valid"
    syncing = "syncing"  # optimistically imported
    invalid = "invalid"
    pre_merge = "pre_merge"


DEFAULT_PRUNE_THRESHOLD = 256


@dataclass
class ProtoNode:
    slot: int
    block_root: bytes
    parent_root: bytes | None
    state_root: bytes
    target_root: bytes
    justified_epoch: int
    finalized_epoch: int
    unrealized_justified_epoch: int
    unrealized_finalized_epoch: int
    execution_status: ExecutionStatus = ExecutionStatus.pre_merge
    execution_block_hash: bytes | None = None
    parent: int | None = None  # index into nodes
    weight: int = 0
    best_child: int | None = None
    best_descendant: int | None = None


class ProtoArrayError(Exception):
    pass


class ProtoArray:
    def __init__(
        self,
        justified_epoch: int,
        finalized_epoch: int,
        prune_threshold: int = DEFAULT_PRUNE_THRESHOLD,
        finalized_root: bytes | None = None,
    ):
        self.prune_threshold = prune_threshold
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.finalized_root = finalized_root
        # advanced by apply_score_changes/find_head; drives the
        # votingSourceEpoch+2 viability tolerance (protoArray.ts
        # nodeIsViableForHead)
        self.current_slot = 0
        self.nodes: list[ProtoNode] = []
        self.indices: dict[bytes, int] = {}

    # -- insertion -----------------------------------------------------

    def on_block(self, node: ProtoNode) -> None:
        """Register a block. Parent must already be known (or None for
        the anchor). protoArray.ts onBlock."""
        if node.block_root in self.indices:
            return
        if node.parent_root is not None:
            parent = self.indices.get(node.parent_root)
            if parent is None:
                raise ProtoArrayError(
                    "unknown parent (blocks must be inserted in order)"
                )
            node.parent = parent
        else:
            node.parent = None
        node_index = len(self.nodes)
        self.indices[node.block_root] = node_index
        self.nodes.append(node)
        if node.parent is not None:
            self._maybe_update_best_child_and_descendant(
                node.parent, node_index
            )

    # -- scoring -------------------------------------------------------

    def apply_score_changes(
        self,
        deltas: list[int],
        justified_epoch: int,
        finalized_epoch: int,
        finalized_root: bytes | None = None,
        current_slot: int | None = None,
    ) -> None:
        """One backward pass: apply vote deltas, bubble weights to
        parents, refresh best child/descendant (protoArray.ts
        applyScoreChanges)."""
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("deltas length mismatch")
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        if finalized_root is not None:
            self.finalized_root = finalized_root
        if current_slot is not None:
            self.current_slot = max(self.current_slot, current_slot)
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.execution_status == ExecutionStatus.invalid:
                # an invalidated node must stay at zero weight no matter
                # what vote movement says; force its applied delta to
                # -weight so stale votes can't drive it negative
                # (protoArray.ts applyScoreChanges nodeDelta)
                delta = -node.weight
            else:
                delta = deltas[i]
            if delta:
                node.weight += delta
                if node.weight < 0:
                    raise ProtoArrayError("negative node weight")
                if node.parent is not None:
                    deltas[node.parent] += delta
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(node.parent, i)

    # -- head ----------------------------------------------------------

    def find_head(
        self, justified_root: bytes, current_slot: int | None = None
    ) -> bytes:
        if current_slot is not None:
            self.current_slot = max(self.current_slot, current_slot)
        idx = self.indices.get(justified_root)
        if idx is None:
            raise ProtoArrayError("unknown justified root")
        node = self.nodes[idx]
        best_idx = (
            node.best_descendant if node.best_descendant is not None else idx
        )
        best = self.nodes[best_idx]
        # reference (protoArray.ts findHead) only runs the viability
        # check when best != justified; an execution-invalid node must
        # never become head in either case, so that part is checked
        # unconditionally
        if best.execution_status == ExecutionStatus.invalid:
            raise ProtoArrayError("head candidate is execution-invalid")
        if best_idx != idx and not self._node_is_viable_for_head(best):
            raise ProtoArrayError(
                "best node is not viable for head (justified/finalized "
                "mismatch or invalid execution)"
            )
        return best.block_root

    # -- execution status (engine verdicts) -----------------------------

    def set_execution_valid(self, block_root: bytes) -> None:
        """Mark a block and all ancestors valid (a valid payload
        validates its ancestry)."""
        idx = self.indices.get(block_root)
        while idx is not None:
            node = self.nodes[idx]
            if node.execution_status == ExecutionStatus.invalid:
                raise ProtoArrayError("valid block has invalid ancestor")
            if node.execution_status != ExecutionStatus.syncing:
                break
            node.execution_status = ExecutionStatus.valid
            idx = node.parent

    def set_execution_invalid(self, block_root: bytes) -> None:
        """Mark a block and all descendants invalid; zero their weights
        (protoArray.ts invalidation on engine INVALID)."""
        start = self.indices.get(block_root)
        if start is None:
            return
        bad = {start}
        self.nodes[start].execution_status = ExecutionStatus.invalid
        self.nodes[start].weight = 0
        for i in range(start + 1, len(self.nodes)):
            node = self.nodes[i]
            if node.parent in bad:
                node.execution_status = ExecutionStatus.invalid
                node.weight = 0
                bad.add(i)
        # recompute best pointers from scratch below the invalid set
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(node.parent, i)

    # -- pruning -------------------------------------------------------

    def prune(self, finalized_root: bytes) -> list[ProtoNode]:
        """Drop everything before the finalized root once enough nodes
        accumulated. Returns removed nodes."""
        idx = self.indices.get(finalized_root)
        if idx is None:
            raise ProtoArrayError("unknown finalized root")
        if idx < self.prune_threshold:
            return []
        removed = self.nodes[:idx]
        kept_set = set()
        keep = []
        remap: dict[int, int] = {}
        for i in range(idx, len(self.nodes)):
            node = self.nodes[i]
            if i == idx or node.parent in kept_set:
                remap[i] = len(keep)
                keep.append(node)
                kept_set.add(i)
            else:
                removed.append(node)
        for node in keep:
            node.parent = (
                remap.get(node.parent) if node.parent is not None else None
            )
            node.best_child = (
                remap.get(node.best_child)
                if node.best_child is not None
                else None
            )
            node.best_descendant = (
                remap.get(node.best_descendant)
                if node.best_descendant is not None
                else None
            )
        anchor = keep[0]
        anchor.parent = None
        self.nodes = keep
        self.indices = {n.block_root: i for i, n in enumerate(self.nodes)}
        return removed

    # -- traversal helpers ---------------------------------------------

    def get_node(self, block_root: bytes) -> ProtoNode | None:
        idx = self.indices.get(block_root)
        return self.nodes[idx] if idx is not None else None

    def is_descendant(self, ancestor_root: bytes, root: bytes) -> bool:
        a = self.indices.get(ancestor_root)
        i = self.indices.get(root)
        if a is None or i is None:
            return False
        while i is not None and i >= a:
            if i == a:
                return True
            i = self.nodes[i].parent
        return False

    def ancestor_at_slot(self, root: bytes, slot: int) -> bytes | None:
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            if node.slot <= slot:
                return node.block_root
            idx = node.parent
        return None

    def iter_chain(self, root: bytes):
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            yield node
            idx = node.parent

    # -- internals -----------------------------------------------------

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """Spec filter_block_tree viability (protoArray.ts
        nodeIsViableForHead): the node's voting source must match the
        store's justified checkpoint or be no more than two epochs
        behind the current epoch, and the node must descend from the
        finalized root."""
        if node.execution_status == ExecutionStatus.invalid:
            return False
        from ..params import preset

        spe = preset().SLOTS_PER_EPOCH
        current_epoch = self.current_slot // spe
        # blocks from a previous epoch are filtered on their unrealized
        # justification (what their state would justify at the epoch
        # boundary); current-epoch blocks on the realized value
        is_from_prev_epoch = node.slot // spe < current_epoch
        voting_source_epoch = (
            node.unrealized_justified_epoch
            if is_from_prev_epoch
            else node.justified_epoch
        )
        correct_justified = (
            self.justified_epoch == 0
            or voting_source_epoch == self.justified_epoch
            or voting_source_epoch + 2 >= current_epoch
        )
        correct_finalized = (
            self.finalized_epoch == 0
            or self._is_finalized_root_or_descendant(node)
        )
        return correct_justified and correct_finalized

    def _is_finalized_root_or_descendant(self, node: ProtoNode) -> bool:
        """True iff node is the store's finalized root or descends from
        it — a conflicting branch with a merely equal finalized_epoch
        must not pass (protoArray.ts isFinalizedRootOrDescendant)."""
        if self.finalized_root is None:
            # root not tracked (legacy callers): fall back to the
            # epoch-only check
            return (
                node.finalized_epoch >= self.finalized_epoch
                or node.unrealized_finalized_epoch >= self.finalized_epoch
            )
        fin_idx = self.indices.get(self.finalized_root)
        if fin_idx is None:
            # finalized block pruned below the anchor; everything we
            # retain descends from it by construction
            return True
        idx: int | None = self.indices.get(node.block_root)
        while idx is not None and idx >= fin_idx:
            if idx == fin_idx:
                return True
            idx = self.nodes[idx].parent
        return False

    def _leads_to_viable_head(self, node: ProtoNode) -> bool:
        # a node leads to a viable head if its best descendant is
        # viable OR it is itself viable — a stale non-viable
        # best_descendant pointer must not disqualify a viable node
        # (protoArray.ts nodeLeadsToViableHead)
        if node.best_descendant is not None and self._node_is_viable_for_head(
            self.nodes[node.best_descendant]
        ):
            return True
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child_and_descendant(
        self, parent_index: int, child_index: int
    ) -> None:
        parent = self.nodes[parent_index]
        child = self.nodes[child_index]
        child_leads = self._leads_to_viable_head(child)

        child_best_descendant = (
            child.best_descendant
            if child.best_descendant is not None
            else child_index
        )

        if parent.best_child == child_index:
            if not child_leads:
                parent.best_child = None
                parent.best_descendant = None
            else:
                parent.best_descendant = child_best_descendant
            return

        if not child_leads:
            return

        if parent.best_child is None:
            parent.best_child = child_index
            parent.best_descendant = child_best_descendant
            return

        best = self.nodes[parent.best_child]
        best_leads = self._leads_to_viable_head(best)
        if not best_leads or (
            child.weight > best.weight
            or (
                child.weight == best.weight
                and child.block_root >= best.block_root
            )
        ):
            parent.best_child = child_index
            parent.best_descendant = child_best_descendant

"""ProtoArray: array-backed block DAG with best-descendant propagation.

Reference analog: packages/fork-choice/src/protoArray/protoArray.ts:15
and computeDeltas.ts — the proto-array fork-choice optimization: nodes
stored parent-before-child in a flat list, weights aggregated in one
backward pass, head lookup O(1) via bestDescendant pointers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum


class ExecutionStatus(str, Enum):
    valid = "valid"
    syncing = "syncing"  # optimistically imported
    invalid = "invalid"
    pre_merge = "pre_merge"


DEFAULT_PRUNE_THRESHOLD = 256


@dataclass
class ProtoNode:
    slot: int
    block_root: bytes
    parent_root: bytes | None
    state_root: bytes
    target_root: bytes
    justified_epoch: int
    finalized_epoch: int
    unrealized_justified_epoch: int
    unrealized_finalized_epoch: int
    execution_status: ExecutionStatus = ExecutionStatus.pre_merge
    execution_block_hash: bytes | None = None
    parent: int | None = None  # index into nodes
    weight: int = 0
    best_child: int | None = None
    best_descendant: int | None = None


class ProtoArrayError(Exception):
    pass


class ProtoArray:
    def __init__(
        self,
        justified_epoch: int,
        finalized_epoch: int,
        prune_threshold: int = DEFAULT_PRUNE_THRESHOLD,
    ):
        self.prune_threshold = prune_threshold
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.nodes: list[ProtoNode] = []
        self.indices: dict[bytes, int] = {}

    # -- insertion -----------------------------------------------------

    def on_block(self, node: ProtoNode) -> None:
        """Register a block. Parent must already be known (or None for
        the anchor). protoArray.ts onBlock."""
        if node.block_root in self.indices:
            return
        if node.parent_root is not None:
            parent = self.indices.get(node.parent_root)
            if parent is None:
                raise ProtoArrayError(
                    "unknown parent (blocks must be inserted in order)"
                )
            node.parent = parent
        else:
            node.parent = None
        node_index = len(self.nodes)
        self.indices[node.block_root] = node_index
        self.nodes.append(node)
        if node.parent is not None:
            self._maybe_update_best_child_and_descendant(
                node.parent, node_index
            )

    # -- scoring -------------------------------------------------------

    def apply_score_changes(
        self,
        deltas: list[int],
        justified_epoch: int,
        finalized_epoch: int,
    ) -> None:
        """One backward pass: apply vote deltas, bubble weights to
        parents, refresh best child/descendant (protoArray.ts
        applyScoreChanges)."""
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("deltas length mismatch")
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            delta = deltas[i]
            if delta:
                node.weight += delta
                if node.weight < 0:
                    raise ProtoArrayError("negative node weight")
                if node.parent is not None:
                    deltas[node.parent] += delta
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(node.parent, i)

    # -- head ----------------------------------------------------------

    def find_head(self, justified_root: bytes) -> bytes:
        idx = self.indices.get(justified_root)
        if idx is None:
            raise ProtoArrayError("unknown justified root")
        node = self.nodes[idx]
        best = (
            self.nodes[node.best_descendant]
            if node.best_descendant is not None
            else node
        )
        if not self._node_is_viable_for_head(best):
            raise ProtoArrayError(
                "best node is not viable for head (justified/finalized "
                "mismatch or invalid execution)"
            )
        return best.block_root

    # -- execution status (engine verdicts) -----------------------------

    def set_execution_valid(self, block_root: bytes) -> None:
        """Mark a block and all ancestors valid (a valid payload
        validates its ancestry)."""
        idx = self.indices.get(block_root)
        while idx is not None:
            node = self.nodes[idx]
            if node.execution_status == ExecutionStatus.invalid:
                raise ProtoArrayError("valid block has invalid ancestor")
            if node.execution_status != ExecutionStatus.syncing:
                break
            node.execution_status = ExecutionStatus.valid
            idx = node.parent

    def set_execution_invalid(self, block_root: bytes) -> None:
        """Mark a block and all descendants invalid; zero their weights
        (protoArray.ts invalidation on engine INVALID)."""
        start = self.indices.get(block_root)
        if start is None:
            return
        bad = {start}
        self.nodes[start].execution_status = ExecutionStatus.invalid
        self.nodes[start].weight = 0
        for i in range(start + 1, len(self.nodes)):
            node = self.nodes[i]
            if node.parent in bad:
                node.execution_status = ExecutionStatus.invalid
                node.weight = 0
                bad.add(i)
        # recompute best pointers from scratch below the invalid set
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(node.parent, i)

    # -- pruning -------------------------------------------------------

    def prune(self, finalized_root: bytes) -> list[ProtoNode]:
        """Drop everything before the finalized root once enough nodes
        accumulated. Returns removed nodes."""
        idx = self.indices.get(finalized_root)
        if idx is None:
            raise ProtoArrayError("unknown finalized root")
        if idx < self.prune_threshold:
            return []
        removed = self.nodes[:idx]
        kept_set = set()
        keep = []
        remap: dict[int, int] = {}
        for i in range(idx, len(self.nodes)):
            node = self.nodes[i]
            if i == idx or node.parent in kept_set:
                remap[i] = len(keep)
                keep.append(node)
                kept_set.add(i)
            else:
                removed.append(node)
        for node in keep:
            node.parent = (
                remap.get(node.parent) if node.parent is not None else None
            )
            node.best_child = (
                remap.get(node.best_child)
                if node.best_child is not None
                else None
            )
            node.best_descendant = (
                remap.get(node.best_descendant)
                if node.best_descendant is not None
                else None
            )
        anchor = keep[0]
        anchor.parent = None
        self.nodes = keep
        self.indices = {n.block_root: i for i, n in enumerate(self.nodes)}
        return removed

    # -- traversal helpers ---------------------------------------------

    def get_node(self, block_root: bytes) -> ProtoNode | None:
        idx = self.indices.get(block_root)
        return self.nodes[idx] if idx is not None else None

    def is_descendant(self, ancestor_root: bytes, root: bytes) -> bool:
        a = self.indices.get(ancestor_root)
        i = self.indices.get(root)
        if a is None or i is None:
            return False
        while i is not None and i >= a:
            if i == a:
                return True
            i = self.nodes[i].parent
        return False

    def ancestor_at_slot(self, root: bytes, slot: int) -> bytes | None:
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            if node.slot <= slot:
                return node.block_root
            idx = node.parent
        return None

    def iter_chain(self, root: bytes):
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            yield node
            idx = node.parent

    # -- internals -----------------------------------------------------

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        if node.execution_status == ExecutionStatus.invalid:
            return False
        # spec filter_block_tree condition with unrealized justification
        # (node counts as viable if its voting source matches the
        # store's justified checkpoint, or it is ahead of it)
        correct_justified = (
            self.justified_epoch == 0
            or node.justified_epoch == self.justified_epoch
            or node.unrealized_justified_epoch >= self.justified_epoch
        )
        correct_finalized = (
            self.finalized_epoch == 0
            or node.finalized_epoch >= self.finalized_epoch
            or node.unrealized_finalized_epoch >= self.finalized_epoch
        )
        return correct_justified and correct_finalized

    def _leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(
                self.nodes[node.best_descendant]
            )
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child_and_descendant(
        self, parent_index: int, child_index: int
    ) -> None:
        parent = self.nodes[parent_index]
        child = self.nodes[child_index]
        child_leads = self._leads_to_viable_head(child)

        child_best_descendant = (
            child.best_descendant
            if child.best_descendant is not None
            else child_index
        )

        if parent.best_child == child_index:
            if not child_leads:
                parent.best_child = None
                parent.best_descendant = None
            else:
                parent.best_descendant = child_best_descendant
            return

        if not child_leads:
            return

        if parent.best_child is None:
            parent.best_child = child_index
            parent.best_descendant = child_best_descendant
            return

        best = self.nodes[parent.best_child]
        best_leads = self._leads_to_viable_head(best)
        if not best_leads or (
            child.weight > best.weight
            or (
                child.weight == best.weight
                and child.block_root >= best.block_root
            )
        ):
            parent.best_child = child_index
            parent.best_descendant = child_best_descendant

"""Fork choice: proto-array LMD-GHOST with proposer boost.

Reference analog: packages/fork-choice (SURVEY.md §2.5) — ProtoArray
(protoArray.ts:15), ForkChoice (forkChoice.ts:80), computeDeltas.
"""

from .fork_choice import Checkpoint, ForkChoice, ForkChoiceError, VoteTracker, compute_deltas
from .proto_array import (
    ExecutionStatus,
    ProtoArray,
    ProtoArrayError,
    ProtoNode,
)

__all__ = [
    "Checkpoint",
    "ExecutionStatus",
    "ForkChoice",
    "ForkChoiceError",
    "ProtoArray",
    "ProtoArrayError",
    "ProtoNode",
    "VoteTracker",
    "compute_deltas",
]

"""Fine-grained timing of run_verify_batch glue at bucket 128."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from lodestar_tpu.bls import kernels  # noqa: E402
from lodestar_tpu.bls.verifier import _rand_scalars  # noqa: E402
from lodestar_tpu.crypto.bls import curve as oc  # noqa: E402
from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.params import BLS_DST_SIG  # noqa: E402

N = 2048


def main() -> None:
    print(f"platform={jax.default_backend()}", flush=True)
    pks, hs, sigs = [], [], []
    for i in range(N):
        sk = 10_000 + i
        h = hash_to_g2(i.to_bytes(32, "little"), BLS_DST_SIG)
        pks.append(oc.g1_mul(oc.G1_GEN, sk))
        hs.append(h)
        sigs.append(oc.g2_mul(h, sk))
    pk = C.g1_batch_from_ints(pks)
    h = C.g2_batch_from_ints(hs)
    sig = C.g2_batch_from_ints(sigs)
    mask = jnp.ones(N, bool)
    bits0 = C.scalars_to_bits(_rand_scalars(N), kernels.RAND_BITS)

    # warm everything
    ok = kernels.run_verify_batch(pk, (h.x, h.y), sig, bits0, mask)
    print("warm ok:", ok, flush=True)

    for rep in range(3):
        t0 = time.perf_counter()
        scalars = _rand_scalars(N)
        t1 = time.perf_counter()
        bits = C.scalars_to_bits(scalars, kernels.RAND_BITS)
        jax.block_until_ready(bits)
        t2 = time.perf_counter()
        anym = bool(np.any(np.asarray(mask)))
        t3 = time.perf_counter()
        out1 = kernels._stage_prepare_batch(pk, h.x, h.y, sig, bits, mask)
        jax.block_until_ready(out1)
        t4 = time.perf_counter()
        f = kernels._stage_miller(*out1[:4])
        jax.block_until_ready(f)
        t5 = time.perf_counter()
        prod = kernels._stage_product(f, out1[4])
        jax.block_until_ready(prod)
        t6 = time.perf_counter()
        fin = kernels._stage_final(prod)
        ok = bool(fin)
        t7 = time.perf_counter()
        print(
            f"rep{rep}: rand={1e3 * (t1 - t0):.1f} bits={1e3 * (t2 - t1):.1f} "
            f"anymask={1e3 * (t3 - t2):.1f} prepare={1e3 * (t4 - t3):.1f} "
            f"miller={1e3 * (t5 - t4):.1f} product={1e3 * (t6 - t5):.1f} "
            f"final+bool={1e3 * (t7 - t6):.1f} total={1e3 * (t7 - t0):.1f} ms",
            flush=True,
        )


if __name__ == "__main__":
    main()

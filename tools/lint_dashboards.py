#!/usr/bin/env python
"""Dashboard lint: panels and the metric catalog must agree BOTH ways.

Forward: walks every `dashboards/*.json` panel target expr, extracts
the metric names the PromQL references, and fails (exit 1) when a name
is not registered by the node's metric catalog — metrics/beacon.py,
metrics/validator_monitor.py, the resilience family, or the tracing
bridge. Histogram bases contribute their `_bucket`/`_sum`/`_count`
series.

Inverse: fails when a REGISTERED metric is referenced by no dashboard
at all (and is not in the explicit ORPHAN_ALLOWLIST below) — a new
metric family that never gets a panel silently rots exactly the way a
deleted metric used to leave a panel flat-lining. Adding a metric
means adding a panel or an allowlist entry, on purpose.

Runs inside tier 1 (tools/run_tests.sh + tests/test_dashboards_lint.py).

Usage: python tools/lint_dashboards.py [dashboards_dir]
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# PromQL functions / keywords / modifiers that look like identifiers
_NOT_METRICS = {
    # aggregations + functions
    "rate", "irate", "increase", "delta", "idelta", "deriv", "resets",
    "histogram_quantile", "quantile", "sum", "min", "max", "avg",
    "count", "count_values", "topk", "bottomk", "stddev", "stdvar",
    "abs", "ceil", "floor", "round", "clamp", "clamp_min", "clamp_max",
    "changes", "absent", "scalar", "vector", "time", "timestamp",
    "label_replace", "label_join", "sort", "sort_desc", "exp", "ln",
    "log2", "log10", "sqrt", "predict_linear", "avg_over_time",
    "min_over_time", "max_over_time", "sum_over_time",
    "count_over_time", "last_over_time", "quantile_over_time",
    # keywords / modifiers / set ops
    "by", "without", "on", "ignoring", "group_left", "group_right",
    "offset", "and", "or", "unless", "bool",
    # special label
    "le",
}

_IDENT = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")

# Registered metrics no dashboard panels yet — each entry is a
# DELIBERATE exception to the inverse lint (log-first or API-first
# series, reference-dashboard name compatibility, raw operands of
# panels that chart a derived form). Everything registered after the
# inverse lint landed (ISSUE 10) must either appear in a dashboard or
# be added here with intent.
ORPHAN_ALLOWLIST = {
    # reference lodestar_bls_thread_pool_* names kept 1:1 for the
    # reference Grafana board (metrics/beacon.py header); the TPU
    # dashboard charts the lodestar_tpu_verifier_* twins instead
    "lodestar_bls_thread_pool_batch_retries_total",
    "lodestar_bls_thread_pool_batch_sigs_success_total",
    "lodestar_bls_thread_pool_batchable_sig_sets_total",
    "lodestar_bls_thread_pool_error_jobs_signature_sets_count",
    "lodestar_bls_thread_pool_job_groups_started_total",
    "lodestar_bls_thread_pool_jobs_started_total",
    "lodestar_bls_thread_pool_prioritized_sig_sets_total",
    "lodestar_bls_thread_pool_sig_sets_total",
    "lodestar_bls_thread_pool_success_jobs_signature_sets_count",
    "lodestar_bls_thread_pool_time_seconds_sum",
    # reqresp: the lodestar_reqresp_* family is the charted one; the
    # beacon_reqresp_* twins keep reference name compatibility
    "beacon_reqresp_incoming_requests_total",
    "beacon_reqresp_outgoing_errors_total",
    "beacon_reqresp_outgoing_requests_total",
    # resilience family: alert-rule operands (breaker/engine state
    # machines), no dedicated board yet
    "lodestar_builder_faults_total",
    "lodestar_execution_engine_http_errors_total",
    "lodestar_execution_engine_http_requests_total",
    "lodestar_execution_engine_state",
    "lodestar_execution_engine_state_transitions_total",
    "lodestar_resilience_breaker_state",
    "lodestar_resilience_breaker_transitions_total",
    "lodestar_resilience_retries_total",
    "lodestar_resilience_retry_giveups_total",
    # eth1 / light-client / sync / forkchoice detail gauges surfaced
    # through the status log line and REST namespaces
    "lodestar_eth1_deposit_count",
    "lodestar_eth1_deposit_tree_size",
    "lodestar_eth1_followed_blocks_count",
    "lodestar_eth1_latest_followed_block_number",
    "lodestar_eth1_update_errors_total",
    "lodestar_lightclient_server_best_updates_count",
    "lodestar_lightclient_server_finality_update_slot",
    "lodestar_lightclient_server_optimistic_update_slot",
    "lodestar_sync_status",
    "lodestar_sync_unknown_block_requests_total",
    "lodestar_forkchoice_indices_count",
    # sim-only series: the scenario fleet's delivered-fault counter
    # (sim/faults.FaultRegistry) — asserted by scenario SLOs and the
    # tier-1 smoke slice, never charted on a production dashboard
    "lodestar_sim_injected_faults_total",
    # raw operands of charted ratios / rollups
    "lodestar_gossip_validation_queue_job_time_seconds",
    "lodestar_oppool_sync_contribution_and_proof_pool_size",
    "validator_monitor_prev_epoch_on_chain_head_attester_hit_total",
    "validator_monitor_prev_epoch_on_chain_target_attester_hit_total",
}


def _build_registry():
    from lodestar_tpu.metrics import (
        RegistryMetricCreator,
        create_lodestar_metrics,
    )
    from lodestar_tpu.metrics.validator_monitor import ValidatorMonitor
    from lodestar_tpu.resilience import create_resilience_metrics

    reg = RegistryMetricCreator()
    create_lodestar_metrics(reg)
    create_resilience_metrics(reg)
    ValidatorMonitor(reg)
    return reg


def registered_metric_families() -> dict[str, set[str]]:
    """base name -> every series name it can expose (histograms add
    their _bucket/_sum/_count children)."""
    from lodestar_tpu.metrics import Histogram

    reg = _build_registry()
    families: dict[str, set[str]] = {}
    for name, metric in reg._metrics.items():
        fam = {name}
        if isinstance(metric, Histogram):
            fam |= {f"{name}_bucket", f"{name}_sum", f"{name}_count"}
        families[name] = fam
    return families


def registered_metric_names() -> set[str]:
    """Every series name the live /metrics endpoint can expose."""
    names: set[str] = set()
    for fam in registered_metric_families().values():
        names |= fam
    return names


def metric_names_in_expr(expr: str) -> set[str]:
    """Identifiers in a PromQL expr that can only be metric names."""
    # strip label matchers {...} (their contents are label names and
    # quoted values) and grouping clauses `by (...)` / `without (...)`
    expr = re.sub(r"\{[^}]*\}", "", expr)
    expr = re.sub(
        r"\b(by|without|on|ignoring|group_left|group_right)\s*"
        r"\(([^)]*)\)",
        " ",
        expr,
    )
    expr = re.sub(r"\[[^\]]*\]", "", expr)  # range selectors [5m]
    expr = re.sub(r'"[^"]*"', "", expr)  # string literals
    return {
        tok
        for tok in _IDENT.findall(expr)
        if tok not in _NOT_METRICS
    }


def iter_panel_exprs(dashboard: dict):
    for panel in dashboard.get("panels", []):
        title = panel.get("title", "<untitled>")
        for target in panel.get("targets", []):
            expr = target.get("expr")
            if expr:
                yield title, expr
        # nested row panels
        for sub in panel.get("panels", []):
            for target in sub.get("targets", []):
                expr = target.get("expr")
                if expr:
                    yield sub.get("title", title), expr


def lint(dash_dir: Path, check_orphans: bool = True) -> int:
    families = registered_metric_families()
    known: set[str] = set()
    for fam in families.values():
        known |= fam
    files = sorted(dash_dir.glob("*.json"))
    if not files:
        print(f"no dashboards found under {dash_dir}", file=sys.stderr)
        return 1
    bad = 0
    referenced: set[str] = set()
    for path in files:
        dashboard = json.loads(path.read_text())
        n_exprs = 0
        unknown: list[tuple[str, str, set]] = []
        for title, expr in iter_panel_exprs(dashboard):
            n_exprs += 1
            names = metric_names_in_expr(expr)
            referenced |= names
            missing = names - known
            if missing:
                unknown.append((title, expr, missing))
        if unknown:
            bad += 1
            print(f"FAIL {path.name}:")
            for title, expr, missing in unknown:
                print(
                    f"  panel {title!r}: unknown metric(s) "
                    f"{sorted(missing)}\n    expr: {expr}"
                )
        else:
            print(f"ok   {path.name}: {n_exprs} exprs, 0 unknown")
    if check_orphans:
        orphans = sorted(
            base
            for base, fam in families.items()
            if not (fam & referenced) and base not in ORPHAN_ALLOWLIST
        )
        if orphans:
            bad += 1
            print(
                "FAIL inverse lint: registered metric(s) referenced by "
                "NO dashboard (add a panel or an ORPHAN_ALLOWLIST "
                "entry):"
            )
            for name in orphans:
                print(f"  {name}")
        else:
            n_allow = sum(
                1
                for base, fam in families.items()
                if not (fam & referenced)
            )
            print(
                f"ok   inverse lint: 0 orphans "
                f"({n_allow} allowlisted, {len(families)} registered)"
            )
    return 1 if bad else 0


if __name__ == "__main__":
    target = (
        Path(sys.argv[1]) if len(sys.argv) > 1 else REPO / "dashboards"
    )
    sys.exit(lint(target))

#!/usr/bin/env python
"""Dashboard lint: every metric a Grafana panel references must exist.

Walks every `dashboards/*.json` panel target expr, extracts the metric
names the PromQL references, and fails (exit 1) when a name is not
registered by the node's metric catalog — metrics/beacon.py,
metrics/validator_monitor.py, the resilience family, or the tracing
bridge. Histogram bases contribute their `_bucket`/`_sum`/`_count`
series.

Runs inside tier 1 (tools/run_tests.sh + tests/test_dashboards_lint.py)
so a renamed or deleted metric can never leave a dashboard silently
flat-lining again.

Usage: python tools/lint_dashboards.py [dashboards_dir]
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# PromQL functions / keywords / modifiers that look like identifiers
_NOT_METRICS = {
    # aggregations + functions
    "rate", "irate", "increase", "delta", "idelta", "deriv", "resets",
    "histogram_quantile", "quantile", "sum", "min", "max", "avg",
    "count", "count_values", "topk", "bottomk", "stddev", "stdvar",
    "abs", "ceil", "floor", "round", "clamp", "clamp_min", "clamp_max",
    "changes", "absent", "scalar", "vector", "time", "timestamp",
    "label_replace", "label_join", "sort", "sort_desc", "exp", "ln",
    "log2", "log10", "sqrt", "predict_linear", "avg_over_time",
    "min_over_time", "max_over_time", "sum_over_time",
    "count_over_time", "last_over_time", "quantile_over_time",
    # keywords / modifiers / set ops
    "by", "without", "on", "ignoring", "group_left", "group_right",
    "offset", "and", "or", "unless", "bool",
    # special label
    "le",
}

_IDENT = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")


def registered_metric_names() -> set[str]:
    """Every series name the live /metrics endpoint can expose."""
    from lodestar_tpu.metrics import (
        Histogram,
        RegistryMetricCreator,
        create_lodestar_metrics,
    )
    from lodestar_tpu.metrics.validator_monitor import ValidatorMonitor
    from lodestar_tpu.resilience import create_resilience_metrics

    reg = RegistryMetricCreator()
    create_lodestar_metrics(reg)
    create_resilience_metrics(reg)
    ValidatorMonitor(reg)
    names: set[str] = set()
    for name, metric in reg._metrics.items():
        names.add(name)
        if isinstance(metric, Histogram):
            names.update(
                {f"{name}_bucket", f"{name}_sum", f"{name}_count"}
            )
    return names


def metric_names_in_expr(expr: str) -> set[str]:
    """Identifiers in a PromQL expr that can only be metric names."""
    # strip label matchers {...} (their contents are label names and
    # quoted values) and grouping clauses `by (...)` / `without (...)`
    expr = re.sub(r"\{[^}]*\}", "", expr)
    expr = re.sub(
        r"\b(by|without|on|ignoring|group_left|group_right)\s*"
        r"\(([^)]*)\)",
        " ",
        expr,
    )
    expr = re.sub(r"\[[^\]]*\]", "", expr)  # range selectors [5m]
    expr = re.sub(r'"[^"]*"', "", expr)  # string literals
    return {
        tok
        for tok in _IDENT.findall(expr)
        if tok not in _NOT_METRICS
    }


def iter_panel_exprs(dashboard: dict):
    for panel in dashboard.get("panels", []):
        title = panel.get("title", "<untitled>")
        for target in panel.get("targets", []):
            expr = target.get("expr")
            if expr:
                yield title, expr
        # nested row panels
        for sub in panel.get("panels", []):
            for target in sub.get("targets", []):
                expr = target.get("expr")
                if expr:
                    yield sub.get("title", title), expr


def lint(dash_dir: Path) -> int:
    known = registered_metric_names()
    files = sorted(dash_dir.glob("*.json"))
    if not files:
        print(f"no dashboards found under {dash_dir}", file=sys.stderr)
        return 1
    bad = 0
    for path in files:
        dashboard = json.loads(path.read_text())
        n_exprs = 0
        unknown: list[tuple[str, str, set]] = []
        for title, expr in iter_panel_exprs(dashboard):
            n_exprs += 1
            missing = metric_names_in_expr(expr) - known
            if missing:
                unknown.append((title, expr, missing))
        if unknown:
            bad += 1
            print(f"FAIL {path.name}:")
            for title, expr, missing in unknown:
                print(
                    f"  panel {title!r}: unknown metric(s) "
                    f"{sorted(missing)}\n    expr: {expr}"
                )
        else:
            print(f"ok   {path.name}: {n_exprs} exprs, 0 unknown")
    return 1 if bad else 0


if __name__ == "__main__":
    target = (
        Path(sys.argv[1]) if len(sys.argv) > 1 else REPO / "dashboards"
    )
    sys.exit(lint(target))

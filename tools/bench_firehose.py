"""Gossip-firehose kernel throughput: same-message batches on the TPU.

BASELINE config #4 shape: the attData-keyed gossip queues emit groups
of (pubkey, signature) pairs on one message; the device runs both
random-weighted MSMs + a 2-pairing check per group
(aggregateWithRandomness fused on device). This measures sustained
sigs/sec with asynchronous dispatch and one deferred verdict readback
per wave — the production readback policy.

Run on the real chip: python tools/bench_firehose.py [group_size waves]
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from lodestar_tpu.bls import kernels  # noqa: E402
from lodestar_tpu.bls.verifier import _rand_scalars  # noqa: E402
from lodestar_tpu.crypto.bls import curve as oc  # noqa: E402
from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.params import BLS_DST_SIG  # noqa: E402


def main() -> None:
    group = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    waves = int(sys.argv[2]) if len(sys.argv) > 2 else 24
    print(
        f"# platform={jax.default_backend()} group={group} waves={waves}",
        file=sys.stderr,
    )
    h = hash_to_g2(b"att-data", BLS_DST_SIG)
    pks, sigs = [], []
    for i in range(group):
        sk = 5000 + i
        pks.append(oc.g1_mul(oc.G1_GEN, sk))
        sigs.append(oc.g2_mul(h, sk))
    pk = C.g1_batch_from_ints(pks)
    hd = C.g2_batch_from_ints([h])
    sig = C.g2_batch_from_ints(sigs)
    mask = jnp.ones(group, bool)

    def submit():
        bits = C.scalars_to_bits(_rand_scalars(group), kernels.RAND_BITS)
        return kernels._run_pipeline(
            kernels._stage_prepare_same_message, pk, (hd.x, hd.y), sig,
            bits, mask,
        )

    all_true = jax.jit(lambda xs: jnp.stack(xs).all())
    ok = bool(all_true([submit(), submit()]))  # warm/compile
    assert ok

    t0 = time.perf_counter()
    oks = [submit() for _ in range(waves)]
    assert bool(all_true(oks))
    dt = time.perf_counter() - t0
    sigs_per_sec = group * waves / dt
    slot_budget = 50_000 / sigs_per_sec
    print(
        f"same-message throughput: {sigs_per_sec:,.0f} sigs/sec "
        f"({group}-sig groups; 50k sigs take {slot_budget:.2f}s of a "
        f"12s slot)"
    )


if __name__ == "__main__":
    main()

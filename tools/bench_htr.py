"""Benchmark: incremental hashTreeRoot on a mainnet-preset beacon state.

VERDICT r1 item 5 done-criterion: importing a block at mainnet preset
with a 100k-validator state must re-hash only changed subtrees. This
measures: cold full hash, warm no-change hash, warm hash after a
block-import-like mutation set (1 proposer + ~128 attestations' worth
of participation flags + a few balances), and structural clone time.

Run: LODESTAR_PRESET=mainnet python tools/bench_htr.py [n_validators]
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("LODESTAR_PRESET", "mainnet")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lodestar_tpu.ssz.cached import clone_value  # noqa: E402
from lodestar_tpu.types import factory  # noqa: E402


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    types = factory.ssz_types()
    ns = types.by_fork["altair"]
    t = ns.BeaconState
    state = t.default()
    far = 2**64 - 1
    for i in range(n):
        state.validators.append(
            types.Validator(
                pubkey=i.to_bytes(48, "little"),
                withdrawal_credentials=(i * 7).to_bytes(32, "little"),
                effective_balance=32_000_000_000,
                slashed=False,
                activation_eligibility_epoch=0,
                activation_epoch=0,
                exit_epoch=far,
                withdrawable_epoch=far,
            )
        )
    state.balances.extend([32_000_000_000] * n)
    state.previous_epoch_participation.extend([7] * n)
    state.current_epoch_participation.extend([0] * n)
    state.inactivity_scores.extend([0] * n)

    t0 = time.perf_counter()
    r0 = t.hash_tree_root(state)
    cold = time.perf_counter() - t0

    t0 = time.perf_counter()
    assert t.hash_tree_root(state) == r0
    nochange = time.perf_counter() - t0

    # block-import-like mutation set
    state.slot += 1
    state.latest_block_header.state_root = b"\x11" * 32
    state.block_roots[state.slot % len(state.block_roots)] = b"\x22" * 32
    state.validators[n // 2].effective_balance += 1
    for i in range(0, 128 * 64, 64):  # ~128 committees' first members
        state.current_epoch_participation[i % n] = 7
    for i in range(16):
        state.balances[(i * 997) % n] += 1000

    t0 = time.perf_counter()
    r1 = t.hash_tree_root(state)
    warm = time.perf_counter() - t0
    assert r1 != r0

    t0 = time.perf_counter()
    cl = clone_value(t, state)
    clone_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    assert t.hash_tree_root(cl) == r1
    clone_hash = time.perf_counter() - t0

    print(
        f"validators={n}\n"
        f"cold_full_hash_s={cold:.3f}\n"
        f"warm_nochange_hash_s={nochange:.4f}\n"
        f"warm_after_block_import_s={warm:.4f}  (speedup {cold / warm:.0f}x)\n"
        f"structural_clone_s={clone_s:.3f}\n"
        f"clone_first_hash_s={clone_hash:.4f}"
    )


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# Tiered test runner (VERDICT r5 task 9).
#
#   tools/run_tests.sh tier1   # fast suite — byte-identical to the
#                              # ROADMAP.md tier-1 verify command
#   tools/run_tests.sh tier2   # slow-marked tests (kernel emulation,
#                              # real-ingest smoke) — parallel via
#                              # pytest-xdist when installed
#   tools/run_tests.sh all     # tier1 then tier2
#
# tier1 is THE gate: keep it green. tier2 is the long tail the
# conftest gates behind LODESTAR_SLOW_TESTS=1 so the fast suite stays
# runnable every round.

set -u
cd "$(dirname "$0")/.."

tier="${1:-tier1}"

run_tier1() {
  # dashboard lint first (also covered by tests/test_dashboards_lint.py
  # inside the pytest run): a dangling panel metric fails the tier
  JAX_PLATFORMS=cpu python tools/lint_dashboards.py || exit 1
  # autotuner offline unit suite, standalone and first (also part of
  # the full pytest run below): the drift-monitor/tuner logic runs
  # with STUBBED kernels, so this gate stays seconds-fast — no real
  # multi-minute ingest compile may ever enter tier-1 through it
  JAX_PLATFORMS=cpu python -m pytest tests/test_autotune.py -q \
    -m 'not slow' -p no:cacheprovider || exit 1
  # device-executor offline suite, standalone and ahead of the main
  # line for the same reason: QoS ordering / admission control /
  # drain-for-retune run against stubbed kernels only, so a scheduling
  # regression surfaces in seconds instead of minutes into the run
  JAX_PLATFORMS=cpu python -m pytest tests/test_device_executor.py -q \
    -m 'not slow' -p no:cacheprovider || exit 1
  # device fault-domain suite (watchdog / taxonomy / quarantine
  # failover / probe reinstatement), standalone and ahead of the main
  # line: it drives the health state machine with manual clocks and
  # stubbed kernels, so a fault-handling regression surfaces in
  # seconds — the deterministic fault drill of the tier
  JAX_PLATFORMS=cpu python -m pytest tests/test_device_health.py -q \
    -m 'not slow' -p no:cacheprovider || exit 1
  # serving fault-domain suite (admission control / brownout ladder /
  # response cache / broadcast SSE / route classification), standalone
  # and ahead of the main line: ManualClock-driven unit tests plus
  # in-process HTTP wire checks, so an overload-policy regression
  # surfaces in seconds — the serving analog of the device suites
  JAX_PLATFORMS=cpu python -m pytest tests/test_api_overload.py -q \
    -m 'not slow' -p no:cacheprovider || exit 1
  # scenario-fleet smoke slice, standalone for the same reason: the
  # two single-process regimes (device-executor blob firehose with
  # the autotuner-holds-still invariant, gossip-burst backpressure)
  # plus the fault-layer unit tests run in seconds; the four
  # multi-node regimes cost minutes each and live in tier 2
  JAX_PLATFORMS=cpu python -m pytest tests/test_scenarios.py \
    tests/test_sim_faults.py -q -m 'not slow' -p no:cacheprovider \
    || exit 1
  # the same slice through the operator CLI: exercises the registry
  # -> SLO-contract -> provenance-stamped artifact path end to end;
  # device_loss_under_load is the injected-fault drill (hang -> wave
  # watchdog -> quarantine -> host failover -> probe reinstatement),
  # lightclient_flood the serving drill (read flood + SSE swarm ->
  # typed sheds on the cheap classes while duty p99 holds)
  JAX_PLATFORMS=cpu python tools/run_scenarios.py \
    --only blob_firehose_under_load,device_loss_under_load,lightclient_flood \
    --json /tmp/lodestar_scenarios_smoke.json || exit 1
  # pytest line matches ROADMAP.md "Tier-1 verify" plus --durations=25:
  # the per-test timing artifact tracks suite-runtime creep per PR
  # (slowest offenders land in /tmp/lodestar_tier1_durations.txt and
  # are echoed below) without perturbing the pass/fail semantics or
  # the DOTS_PASSED progress-line count
  set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --durations=25 --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
  # extract the "slowest durations" block into its own artifact and
  # surface the top offenders so runtime creep is visible in every run
  awk '/^=+ slowest .* durations =+$/{on=1} /^=/ && !/durations/{on=0} on{print}' /tmp/_t1.log > /tmp/lodestar_tier1_durations.txt
  if [ -s /tmp/lodestar_tier1_durations.txt ]; then
    echo "tier-1 slowest tests (full list: /tmp/lodestar_tier1_durations.txt):"
    grep -aE '^[0-9]+\.[0-9]+s' /tmp/lodestar_tier1_durations.txt | head -8
  fi
  exit $rc
}

run_tier2() {
  # slow tests; -n auto when pytest-xdist is present (the container
  # this repo grew in does not ship it — serial fallback, no install)
  local xdist_args=()
  if python -c "import xdist" >/dev/null 2>&1; then
    xdist_args=(-n auto)
  else
    echo "pytest-xdist not installed: running tier-2 serially" >&2
  fi
  LODESTAR_SLOW_TESTS=1 JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m slow \
    --continue-on-collection-errors -p no:cacheprovider \
    "${xdist_args[@]}"
}

case "$tier" in
  tier1) run_tier1 ;;
  tier2) run_tier2 ;;
  all)
    ( run_tier1 )
    t1=$?
    run_tier2
    t2=$?
    exit $(( t1 || t2 ))
    ;;
  *)
    echo "usage: $0 [tier1|tier2|all]" >&2
    exit 2
    ;;
esac

"""Per-stage steady-state timing of the batch-verify pipeline at bucket
128 on the default platform. Run after bench.py has warmed the cache."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from lodestar_tpu.bls import kernels  # noqa: E402
from lodestar_tpu.bls.verifier import _rand_scalars  # noqa: E402
from lodestar_tpu.crypto.bls import curve as oc  # noqa: E402
from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.params import BLS_DST_SIG  # noqa: E402

N = 128


def t(label, fn, reps=3):
    fn()  # warm
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
        jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{label}: {dt * 1000:.2f} ms", flush=True)
    return out


def main() -> None:
    print(f"platform={jax.default_backend()}", flush=True)
    pks, hs, sigs = [], [], []
    for i in range(N):
        sk = 10_000 + i
        h = hash_to_g2(i.to_bytes(32, "little"), BLS_DST_SIG)
        pks.append(oc.g1_mul(oc.G1_GEN, sk))
        hs.append(h)
        sigs.append(oc.g2_mul(h, sk))
    pk = C.g1_batch_from_ints(pks)
    h = C.g2_batch_from_ints(hs)
    sig = C.g2_batch_from_ints(sigs)
    mask = jnp.ones(N, bool)

    t0 = time.perf_counter()
    bits = C.scalars_to_bits(_rand_scalars(N), kernels.RAND_BITS)
    jax.block_until_ready(bits)
    print(f"host rand+bits: {(time.perf_counter() - t0) * 1000:.2f} ms")

    prep = t(
        "stage prepare",
        lambda: kernels._stage_prepare_batch(pk, h.x, h.y, sig, bits, mask),
    )
    px, py, qx, qy, full_mask = prep
    f = t("stage miller", lambda: kernels._stage_miller(px, py, qx, qy))
    prod = t("stage product", lambda: kernels._stage_product(f, full_mask))
    t("stage final", lambda: kernels._stage_final(prod))

    def whole():
        b = C.scalars_to_bits(_rand_scalars(N), kernels.RAND_BITS)
        return kernels.run_verify_batch(pk, (h.x, h.y), sig, b, mask)

    t0 = time.perf_counter()
    for _ in range(3):
        assert whole() is True
    print(
        f"whole verify: {(time.perf_counter() - t0) / 3 * 1000:.2f} ms",
        flush=True,
    )


if __name__ == "__main__":
    main()

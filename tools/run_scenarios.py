#!/usr/bin/env python
"""Run the scenario fleet (lodestar_tpu/sim/scenarios.py) and emit a
provenance-stamped SCENARIOS.json.

Each scenario is a named, deterministic adversity regime with a
machine-evaluated SLO contract; this CLI runs a profile of the
registry and exits non-zero when any SLO row (or scenario body)
failed — the CI shape: tier 1 runs a fast smoke slice through
tools/run_tests.sh, tier 2 runs the full profiles.

Usage:
  python tools/run_scenarios.py                        # all, smoke
  python tools/run_scenarios.py --profile full
  python tools/run_scenarios.py --only reorg_storm,blob_firehose_under_load
  python tools/run_scenarios.py --list
  python tools/run_scenarios.py --json SCENARIOS.json  # artifact path
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Same hermetic setup as tests/conftest.py: the fleet's slot counts
# and committee shapes assume the minimal preset, and the runs must
# be reproducible on the virtual CPU backend regardless of the
# ambient JAX_PLATFORMS pin.
os.environ.setdefault("LODESTAR_PRESET", "minimal")
os.environ["JAX_PLATFORMS"] = "cpu"
if "jax" in sys.modules:  # sitecustomize may have imported jax early
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--profile", choices=("smoke", "full"),
                    default="smoke")
    ap.add_argument("--only", default=None,
                    help="comma-separated scenario names")
    ap.add_argument("--seed", type=int, default=20260807)
    ap.add_argument("--json", dest="json_path", default=None,
                    help="write the result artifact here "
                         "(default: <repo>/SCENARIOS.json)")
    ap.add_argument("--no-json", action="store_true",
                    help="skip writing the artifact")
    ap.add_argument("--list", action="store_true",
                    help="list registered scenarios and exit")
    args = ap.parse_args(argv)

    from lodestar_tpu.sim.scenarios import SCENARIOS, run_all

    if args.list:
        for name, spec in SCENARIOS.items():
            print(f"{name}: {spec.summary}")
            print(f"    faults: {', '.join(spec.faults)}")
            print(f"    slos:   {', '.join(spec.slo_names)}")
        return 0

    only = (
        [n.strip() for n in args.only.split(",") if n.strip()]
        if args.only
        else None
    )
    results = run_all(profile=args.profile, seed=args.seed, only=only)
    for res in results:
        print(res.summary())
        if res.error:
            print(res.error, file=sys.stderr)

    n_pass = sum(1 for r in results if r.passed)
    print(f"\n{n_pass}/{len(results)} scenarios passed "
          f"[{args.profile}, seed={args.seed}]")

    if not args.no_json:
        from lodestar_tpu.utils.provenance import provenance

        artifact = {
            "profile": args.profile,
            "seed": args.seed,
            "passed": n_pass == len(results),
            "results": [r.to_dict() for r in results],
            "provenance": provenance(),
        }
        path = Path(args.json_path) if args.json_path else (
            REPO / "SCENARIOS.json"
        )
        path.write_text(json.dumps(artifact, indent=2) + "\n")
        print(f"wrote {path}")

    return 0 if n_pass == len(results) else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Peak-DA blob firehose: KZG batch verification through the MSM tiers.

The DA analog of bench.py: drives `verify_blob_kzg_proof_batch` with
max-blobs-per-block batches — the load shape a deneb node sees when
every block arrives full — through the selected MSM backend tier
(crypto/kzg.py: device Pippenger / host C / pure-Python oracle) and
records blobs/s, per-batch latency, and the per-path dispatch counters
in a provenance-stamped BENCH_blobs.json. `--with-commitment`
additionally times the producer-side 4096-point Lagrange lincomb
(blob_to_kzg_commitment) per tier.

Run on the real chip:  python tools/bench_blobs.py --real --backend auto
CPU smoke (honest 1-core-emulation numbers):
                       python tools/bench_blobs.py --blocks 2

`--autotune-from AUTOTUNE.json` replays a recorded device decision
(msm_window included) before measuring, like bench.py.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def build_batch(n_blobs: int):
    """n valid (blob, commitment, proof) triples via the native tier
    (fixture prep is not the thing measured)."""
    from hashlib import sha256

    from lodestar_tpu.crypto import kzg

    blobs, comms, proofs = [], [], []
    for s in range(n_blobs):
        out = bytearray()
        for i in range(kzg.FIELD_ELEMENTS_PER_BLOB):
            v = (
                int.from_bytes(
                    sha256(
                        s.to_bytes(8, "little") + i.to_bytes(8, "little")
                    ).digest(),
                    "big",
                )
                % kzg.BLS_MODULUS
            )
            out += v.to_bytes(32, "big")
        blob = bytes(out)
        c = kzg.blob_to_kzg_commitment(blob)
        blobs.append(blob)
        comms.append(c)
        proofs.append(kzg.compute_blob_kzg_proof(blob, c))
    return blobs, comms, proofs


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--blobs",
        type=int,
        default=None,
        help="blobs per batch (default: the preset's max blobs/block)",
    )
    p.add_argument(
        "--blocks", type=int, default=4, help="batches to verify"
    )
    p.add_argument(
        "--backend",
        default=None,
        choices=("auto", "device", "native", "oracle"),
        help="MSM backend tier (default: leave the live mode)",
    )
    p.add_argument(
        "--window",
        type=int,
        default=None,
        help="Pippenger window override (ops/msm.py)",
    )
    p.add_argument(
        "--with-commitment",
        action="store_true",
        help="also time blob_to_kzg_commitment (the 4096-point "
        "Lagrange lincomb) through the selected tier",
    )
    p.add_argument("--json-out", default="BENCH_blobs.json")
    p.add_argument(
        "--autotune-from",
        default=None,
        help="replay a recorded autotune decision before measuring",
    )
    p.add_argument(
        "--real",
        action="store_true",
        help="require a TPU backend (this bench measures hardware; "
        "without --real a CPU run is accepted and stamped as the "
        "1-core emulation it is)",
    )
    args = p.parse_args()

    import jax

    from lodestar_tpu.crypto import kzg
    from lodestar_tpu.params import preset
    from lodestar_tpu.utils import jaxcache
    from lodestar_tpu.utils.provenance import provenance

    jaxcache.enable()
    platform = jax.default_backend()
    if args.real and platform != "tpu":
        print(
            f"--real: platform is {platform!r}, not 'tpu'. Run on the "
            "TPU host (REAL_CAMPAIGN.md step 'blobs').",
            file=sys.stderr,
        )
        return 2
    if args.autotune_from:
        from lodestar_tpu.device import autotune

        autotune.apply_decision(
            autotune.load_decision(args.autotune_from)
        )
    if args.window is not None:
        from lodestar_tpu.ops import msm

        msm.set_msm_window(args.window)

    n_blobs = args.blobs or preset().MAX_BLOBS_PER_BLOCK
    print(
        f"# platform={platform} backend={args.backend or kzg.msm_backend()} "
        f"blobs/block={n_blobs} blocks={args.blocks}",
        file=sys.stderr,
    )
    kzg.activate_trusted_setup(kzg.dev_trusted_setup())
    # fixture prep stays on the live (host) tier — the producer-side
    # lincombs are not the thing measured; the selected backend takes
    # over for the verify loop below
    t0 = time.perf_counter()
    blobs, comms, proofs = build_batch(n_blobs)
    prep_s = time.perf_counter() - t0
    if args.backend is not None:
        kzg.set_msm_backend(args.backend)

    # warm the verify path (first call may pay the device compile /
    # persistent-cache load; steady state is what a node sees)
    assert kzg.verify_blob_kzg_proof_batch(blobs, comms, proofs)
    warm_s = time.perf_counter() - t0 - prep_s

    # per-path evidence for the MEASURED loop only: the process
    # counters also carry fixture prep + the warm call, so record the
    # delta — the artifact must show which tier the timed blocks ran
    counts_before = kzg.msm_path_counts()
    times = []
    for _ in range(args.blocks):
        t0 = time.perf_counter()
        ok = kzg.verify_blob_kzg_proof_batch(blobs, comms, proofs)
        times.append(time.perf_counter() - t0)
        assert ok
    counts_measured = {
        k: v - counts_before.get(k, 0)
        for k, v in kzg.msm_path_counts().items()
    }
    per_block = min(times)
    blobs_per_sec = n_blobs / per_block

    result = {
        "workload": "verify_blob_kzg_proof_batch (peak-DA firehose)",
        "blobs_per_block": n_blobs,
        "blocks": args.blocks,
        "msm_backend_mode": kzg.msm_backend(),
        "fixture_prep_seconds": round(prep_s, 3),
        "warm_first_verify_seconds": round(warm_s, 3),
        "seconds_per_block_best": round(per_block, 4),
        "seconds_per_block_all": [round(t, 4) for t in times],
        "blobs_per_sec": round(blobs_per_sec, 2),
        "msm_path_counts_measured": counts_measured,
        "msm_path_counts_process": kzg.msm_path_counts(),
    }
    if args.with_commitment:
        t0 = time.perf_counter()
        c = kzg.blob_to_kzg_commitment(blobs[0])
        result["commitment_lincomb_seconds"] = round(
            time.perf_counter() - t0, 3
        )
        result["commitment_matches_fixture"] = c == comms[0]
    payload = {**result, "provenance": provenance()}
    with open(args.json_out, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    print(
        f"peak-DA batch verify: {blobs_per_sec:,.1f} blobs/s "
        f"({n_blobs}-blob blocks, {per_block * 1000:.1f} ms/block best; "
        f"measured-loop paths {counts_measured}) -> {args.json_out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Per-stage timing of the PRODUCTION 2048-set ingest pipeline with
forced readbacks (device_get on a leaf) — block_until_ready alone does
not force remote execution over the tunneled backend. Run after
bench.py so all stages hit the persistent compile cache."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from lodestar_tpu.bls import kernels  # noqa: E402
from lodestar_tpu.bls import api as bls_api  # noqa: E402
from lodestar_tpu.bls.verifier import _rand_scalars  # noqa: E402
from lodestar_tpu.crypto.bls import curve as oc  # noqa: E402
from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.params import BLS_DST_SIG  # noqa: E402

N = 2048
KEYS = 256


def force(x):
    """Force + wait: read one scalar back from the device."""
    leaves = jax.tree.leaves(x)
    for leaf in leaves:
        np.asarray(jax.device_get(leaf))
    return x


def t(label, fn, reps=2):
    force(fn())  # warm/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = force(fn())
    dt = (time.perf_counter() - t0) / reps
    print(f"{label}: {dt * 1000:.1f} ms", flush=True)
    return out


def main() -> None:
    print(f"platform={jax.default_backend()} N={N}", flush=True)
    # build N sets over KEYS distinct keys, like bench.py
    pks, hs, sig_bytes = [], [], []
    key_pts = {}
    for i in range(N):
        sk = 10_000 + (i % KEYS)
        if sk not in key_pts:
            key_pts[sk] = oc.g1_mul(oc.G1_GEN, sk)
        msg = i.to_bytes(32, "little")
        h = hash_to_g2(msg, BLS_DST_SIG)
        pks.append(key_pts[sk])
        hs.append((msg, h))
        sig_bytes.append(oc.g2_to_bytes(oc.g2_mul(h, sk)))

    t0 = time.perf_counter()
    pk = C.g1_batch_from_ints(pks)
    sig_x0, sig_x1, sig_sign = [], [], []
    u0l, u1l = [], []
    for (msg, _h), sb in zip(hs, sig_bytes):
        xc0, xc1, sgn, ok = bls_api.parse_signature(sb)
        assert ok
        sig_x0.append(xc0)
        sig_x1.append(xc1)
        sig_sign.append(sgn)
        d = bls_api.message_draws(msg)
        u0l.append(d[0])
        u1l.append(d[1])
    from lodestar_tpu.ops import limbs as L

    sig_x = (L.from_ints(sig_x0), L.from_ints(sig_x1))
    sign_arr = jnp.asarray(np.asarray(sig_sign, np.int32))
    u0 = (L.from_ints([u[0] for u in u0l]), L.from_ints([u[1] for u in u0l]))
    u1 = (L.from_ints([u[0] for u in u1l]), L.from_ints([u[1] for u in u1l]))
    mask = jnp.ones(N, bool)
    bits = C.scalars_to_bits(_rand_scalars(N), kernels.RAND_BITS)
    print(f"host prep: {(time.perf_counter() - t0) * 1000:.0f} ms", flush=True)

    sqrt_out = t(
        "g2_sqrt (pallas chains)",
        lambda: kernels._stage_g2_sqrt(sig_x, sign_arr),
    )
    x, y, is_qr = sqrt_out
    sub_out = t(
        "g2_subgroup",
        lambda: kernels._stage_g2_subgroup(x, y, is_qr, mask),
    )
    sig, all_valid = sub_out
    iso = t("sswu+iso", lambda: kernels._stage_sswu_iso(u0, u1))
    cof = t("cofactor+affine", lambda: kernels._stage_cofactor(iso, mask))
    hx, hy = cof
    prep = t(
        "prepare (ladders+aggregate+affine)",
        lambda: kernels._stage_prepare_batch(pk, hx, hy, sig, bits, mask),
    )
    px, py, qx, qy, pair_mask = prep
    f = t("miller", lambda: kernels._stage_miller(px, py, qx, qy))
    prod = t("product", lambda: kernels._stage_product(f, pair_mask))
    t("final_exp", lambda: kernels._stage_final_with_valid(prod, all_valid))

    # end-to-end async pipeline (what the verifier dispatches)
    def full():
        return kernels.run_verify_batch_ingest_async(
            pk, sig_x, sign_arr, u0, u1, bits, mask
        )

    t("FULL pipeline", full)


if __name__ == "__main__":
    main()

"""True per-stage device cost at bucket 2048: each probe jits the
stage + a scalar reduction, so one call = dispatch + device + ONE
readback (~100 ms baseline, printed first)."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from lodestar_tpu.bls import api as bls_api  # noqa: E402
from lodestar_tpu.bls import kernels  # noqa: E402
from lodestar_tpu.bls.verifier import _rand_scalars  # noqa: E402
from lodestar_tpu.crypto.bls import curve as oc  # noqa: E402
from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.ops import limbs as L  # noqa: E402
from lodestar_tpu.params import BLS_DST_SIG  # noqa: E402
from lodestar_tpu.utils import jaxcache  # noqa: E402

jaxcache.enable()
N = 2048
KEYS = 256


def _scalarize(out):
    acc = jnp.int32(0)
    for leaf in jax.tree.leaves(out):
        acc = acc + jnp.sum(leaf.astype(jnp.int32) if leaf.dtype == jnp.bool_ else leaf, dtype=jnp.int32)
    return acc


def t(label, fn, *args, reps=3):
    wrapped = jax.jit(lambda *a: _scalarize(fn(*a)))
    np.asarray(jax.device_get(wrapped(*args)))
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(jax.device_get(wrapped(*args)))
    print(f"{label}: {(time.perf_counter() - t0) / reps * 1000:.1f} ms", flush=True)


def main():
    print(f"platform={jax.default_backend()} N={N}", flush=True)
    pks, sig_x0, sig_x1, sig_sign, u0l, u1l = [], [], [], [], [], []
    for i in range(N):
        sk = 10_000 + (i % KEYS)
        msg = i.to_bytes(32, "little")
        h = hash_to_g2(msg, BLS_DST_SIG)
        pks.append(oc.g1_mul(oc.G1_GEN, sk))
        sb = oc.g2_to_bytes(oc.g2_mul(h, sk))
        xc0, xc1, sgn, ok = bls_api.parse_signature(sb)
        sig_x0.append(xc0)
        sig_x1.append(xc1)
        sig_sign.append(sgn)
        d = bls_api.message_draws(msg)
        u0l.append(d[0])
        u1l.append(d[1])
    pk = C.g1_batch_from_ints(pks)
    sig_x = (L.from_ints(sig_x0), L.from_ints(sig_x1))
    sign_arr = jnp.asarray(np.asarray(sig_sign, np.int32))
    u0 = (L.from_ints([u[0] for u in u0l]), L.from_ints([u[1] for u in u0l]))
    u1 = (L.from_ints([u[0] for u in u1l]), L.from_ints([u[1] for u in u1l]))
    mask = jnp.ones(N, bool)
    bits = C.scalars_to_bits(_rand_scalars(N), kernels.RAND_BITS)

    t("null", lambda x: x, mask, reps=5)
    t("g2_sqrt", kernels._stage_g2_sqrt.__wrapped__, sig_x, sign_arr)
    x, y, is_qr = kernels._stage_g2_sqrt(sig_x, sign_arr)
    t("g2_subgroup", kernels._stage_g2_subgroup.__wrapped__, x, y, is_qr, mask)
    sig, all_valid = kernels._stage_g2_subgroup(x, y, is_qr, mask)
    t("sswu_iso", kernels._stage_sswu_iso.__wrapped__, u0, u1)
    iso = kernels._stage_sswu_iso(u0, u1)
    t("cofactor", kernels._stage_cofactor.__wrapped__, iso, mask)
    hx, hy = kernels._stage_cofactor(iso, mask)
    t("prepare", kernels._stage_prepare_batch.__wrapped__, pk, hx, hy, sig, bits, mask)
    px, py, qx, qy, pm = kernels._stage_prepare_batch(pk, hx, hy, sig, bits, mask)
    t("miller", lambda a, b, c, d: kernels._stage_miller(a, b, c, d), px, py, qx, qy)
    f = kernels._stage_miller(px, py, qx, qy)
    t("product", lambda ff, m: kernels._stage_product(ff, m), f, pm)
    prod = kernels._stage_product(f, pm)
    t("final", lambda p2, v: kernels._stage_final_with_valid(p2, v), prod, all_valid)


if __name__ == "__main__":
    main()

"""Differential check: Pallas Miller/pow_u kernels vs the XLA scan path
on the current backend (run on the real TPU; CPU uses interpret mode and
is very slow — prefer tests/test_pallas_pairing.py there).

Both implementations are polynomial maps, so arbitrary canonical field
elements exercise every formula — no curve setup needed. Also times the
kernels at the production bucket shape (2049 pairs).
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from lodestar_tpu.crypto.bls.fields import P  # noqa: E402
from lodestar_tpu.ops import limbs as L  # noqa: E402
from lodestar_tpu.ops import pairing, pallas_pairing, tower  # noqa: E402
from lodestar_tpu.utils import jaxcache  # noqa: E402

jaxcache.enable()
rng = np.random.default_rng(7)


def rand_fq(n):
    return L.from_ints([int(rng.integers(0, 2**63)) ** 7 % P for _ in range(n)])


def rand_fq2(n):
    return (rand_fq(n), rand_fq(n))


def fq12_ints(f):
    return [L.to_ints(lv) for c6 in f for c2 in c6 for lv in c2]


def check(label, a, b):
    xs, ys = fq12_ints(a), fq12_ints(b)
    ok = all(np.array_equal(x, y) for x, y in zip(xs, ys))
    print(f"{label}: {'OK' if ok else 'MISMATCH'}", flush=True)
    if not ok:
        for i, (x, y) in enumerate(zip(xs, ys)):
            if not np.array_equal(x, y):
                print(f"  comp {i}: {x[:2]} vs {y[:2]}")
        sys.exit(1)


def timeit(label, fn, reps=3):
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn())
    print(f"{label}: {(time.perf_counter() - t0) / reps * 1000:.1f} ms", flush=True)


def main():
    print(f"platform={jax.default_backend()}", flush=True)
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8

    px, py = rand_fq(n), rand_fq(n)
    qx, qy = rand_fq2(n), rand_fq2(n)
    t0 = time.perf_counter()
    f_pal = pallas_pairing.miller_loop(px, py, qx, qy)
    jax.block_until_ready(f_pal[0][0][0].v)
    print(f"miller pallas compile+run: {time.perf_counter() - t0:.1f} s", flush=True)
    t0 = time.perf_counter()
    f_xla = pairing.miller_loop(px, py, qx, qy)
    jax.block_until_ready(f_xla[0][0][0].v)
    print(f"miller xla compile+run: {time.perf_counter() - t0:.1f} s", flush=True)
    check("miller", f_pal, f_xla)

    g = tuple(
        tuple((rand_fq(n), rand_fq(n)) for _ in range(3)) for _ in range(2)
    )
    t0 = time.perf_counter()
    p_pal = pallas_pairing.pow_u(g)
    jax.block_until_ready(p_pal[0][0][0].v)
    print(f"pow_u pallas compile+run: {time.perf_counter() - t0:.1f} s", flush=True)
    p_xla = pairing._pow_u(g)
    check("pow_u", p_pal, p_xla)

    # scalar-shape pow_u (the production final-exp shape)
    g1 = jax.tree.map(lambda t: t[0], g)
    check("pow_u scalar", pallas_pairing.pow_u(g1), pairing._pow_u(g1))

    if jax.default_backend() == "tpu":
        N = 2049
        px, py = rand_fq(N), rand_fq(N)
        qx, qy = rand_fq2(N), rand_fq2(N)
        timeit(
            f"miller pallas n={N}",
            lambda: pallas_pairing.miller_loop(px, py, qx, qy)[0][0][0].v,
        )
        timeit(
            "final_exp pallas (scalar)",
            lambda: pallas_pairing.final_exponentiation(g1)[0][0][0].v,
        )
    print("all checks passed")


if __name__ == "__main__":
    main()

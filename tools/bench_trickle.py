"""Gossip-shaped trickle benchmark: the small-bucket steady state.

BENCH_r05 measures bulk waves (16x128-set jobs); the PRODUCTION
steady state is the opposite shape — same-message groups of a few
dozen sigs flushed by the attData-keyed queues, plus single
aggregate-and-proof sets dripping in between. This drives
`TpuBlsVerifier` with exactly that arrival pattern and reports, per
group size {1, 16, 32, 128}:

  - sustained sigs/s over the whole trickle
  - p50 / p99 submit-to-verdict latency (caller-observed, which
    includes the gossip buffer + rolling-bucket wait by design)

plus the verifier's per-bucket-size / per-path dispatch counters —
the proof of whether trickle traffic coalesced into device-ingest
buckets (continuous batching) or fell down the host-path cliff.

Default mode is sized for this container's CPU XLA (no TPU attached:
absolute numbers measure a 1-core host emulating the device and are
committed as the honest artifact this environment can produce; see
the caveat field in the JSON). `--real` runs the production shape on
an attached TPU. `--no-rolling` disables continuous batching
(latency budget 0) for an A/B pair.

  python tools/bench_trickle.py --json-out BENCH_trickle.json
  python tools/bench_trickle.py --real --json-out BENCH_trickle.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

sys.path.insert(0, ".")


def _build_single_sets(n: int):
    """n independent 1-set jobs (gossip aggregate-and-proof shape)."""
    from lodestar_tpu.bls import SignatureSet
    from lodestar_tpu.crypto.bls import curve as oc
    from lodestar_tpu.crypto.bls import native
    from lodestar_tpu.params import BLS_DST_SIG

    dst = bytes(BLS_DST_SIG)
    out = []
    for i in range(n):
        sk = 3 + i % 512
        msg = (900_000 + i).to_bytes(32, "little")
        h = native.hash_to_g2(msg, dst)
        pk = oc.g1_to_bytes(native.g1_mul(oc.G1_GEN, sk))
        s = oc.g2_to_bytes(native.g2_mul(h, sk))
        out.append([SignatureSet(pk, msg, s)])
    return out


def _build_same_message_group(size: int, tag: int):
    """One attData-keyed group: `size` (pubkey, signature) pairs on a
    shared message (unaggregated-attestation shape)."""
    from lodestar_tpu.bls import SameMessageSet
    from lodestar_tpu.crypto.bls import curve as oc
    from lodestar_tpu.crypto.bls import native
    from lodestar_tpu.params import BLS_DST_SIG

    msg = (800_000 + tag).to_bytes(32, "little")
    h = native.hash_to_g2(msg, bytes(BLS_DST_SIG))
    pairs = []
    for i in range(size):
        sk = 7 + (tag * size + i) % 512
        pairs.append(
            SameMessageSet(
                oc.g1_to_bytes(native.g1_mul(oc.G1_GEN, sk)),
                oc.g2_to_bytes(native.g2_mul(h, sk)),
            )
        )
    return pairs, msg


async def _run_trickle(
    v,
    singles,
    groups,
    gap_s: float,
):
    """Submit the schedule as a trickle (one item every gap_s) and
    gather caller-observed latencies per group size."""
    lat: dict[int, list[float]] = {}
    t_start = time.perf_counter()
    tasks = []

    async def one_single(sets):
        t0 = time.perf_counter()
        ok = await v.verify_signature_sets(sets, batchable=True)
        lat.setdefault(1, []).append(time.perf_counter() - t0)
        return ok

    async def one_group(pairs, msg):
        t0 = time.perf_counter()
        res = await v.verify_signature_sets_same_message(pairs, msg)
        lat.setdefault(len(pairs), []).append(
            time.perf_counter() - t0
        )
        return all(res)

    # interleave: groups spaced through the single-set drip
    schedule: list = [("s", s) for s in singles]
    stride = max(1, len(schedule) // max(1, len(groups)))
    for i, g in enumerate(groups):
        schedule.insert(min(len(schedule), (i + 1) * stride), ("g", g))
    for kind, item in schedule:
        if kind == "s":
            tasks.append(asyncio.ensure_future(one_single(item)))
        else:
            tasks.append(
                asyncio.ensure_future(one_group(item[0], item[1]))
            )
        await asyncio.sleep(gap_s)
    oks = await asyncio.gather(*tasks)
    wall = time.perf_counter() - t_start
    if not all(oks):
        raise RuntimeError("trickle verify returned False on valid sigs")
    return lat, wall


def _quantile(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    if not ys:
        return 0.0
    i = min(len(ys) - 1, int(q * len(ys)))
    return ys[i]


async def _bench(args) -> dict:
    from lodestar_tpu.bls import TpuBlsVerifier
    from lodestar_tpu.bls import kernels as K

    if args.autotune_from:
        # replay a recorded autotune decision (device/autotune.py):
        # the trickle then measures the tuner's configuration. Like
        # --ingest-min-bucket below, an EXPLICIT --latency-budget-ms
        # wins over the replayed value (A/B runs against the tuned
        # config must be possible).
        from lodestar_tpu.device import autotune as _at

        cfg = _at.apply_decision(_at.load_decision(args.autotune_from))
        if args.latency_budget_ms is None:
            args.latency_budget_ms = cfg.latency_budget_ms
    if args.latency_budget_ms is None:
        args.latency_budget_ms = 50
    if args.ingest_min_bucket is not None:
        K.set_ingest_min_bucket(args.ingest_min_bucket)

    group_sizes = (
        (16, 32, 128) if args.real else tuple(args.group_sizes)
    )
    n_singles = args.singles
    reps = args.group_reps
    singles = _build_single_sets(n_singles)
    groups = []
    tag = 0
    for _ in range(reps):
        for gs in group_sizes:
            groups.append(_build_same_message_group(gs, tag))
            tag += 1

    v = TpuBlsVerifier(
        latency_budget_ms=0 if args.no_rolling else args.latency_budget_ms,
        pipeline_depth=args.pipeline_depth,
    )
    if args.warmup:
        v.start_warmup(block=True)

    # warmup pass: compile every bucket shape this schedule touches so
    # the measured trickle sees a WARM node (production steady state)
    warm_lat, _ = await _run_trickle(
        v,
        _build_single_sets(min(8, n_singles)),
        [
            _build_same_message_group(gs, 10_000 + i)
            for i, gs in enumerate(group_sizes)
        ],
        args.gap_ms / 1000.0,
    )
    m = v.metrics
    # reset counters so the report covers only the measured run
    from lodestar_tpu.bls.verifier import LatencyHistogram

    m.dispatch_by_bucket = {}
    m.dispatch_by_path = {k: 0 for k in m.dispatch_by_path}
    m.rolling_flushes = {k: 0 for k in m.rolling_flushes}
    m.verify_latency = LatencyHistogram()
    m.same_message_latency = LatencyHistogram()

    lat, wall = await _run_trickle(
        v, singles, groups, args.gap_ms / 1000.0
    )
    depth = v.pipeline_depth()
    await v.close()

    total_sigs = n_singles + reps * sum(group_sizes)
    # overlapped-vs-sync A/B: when the measured run overlapped waves
    # (depth > 1), repeat the SAME schedule synchronously (depth 1,
    # every program already warm) so the report carries both columns
    sync_wall = None
    if depth > 1:
        v_sync = TpuBlsVerifier(
            latency_budget_ms=(
                0 if args.no_rolling else args.latency_budget_ms
            ),
            pipeline_depth=1,
        )
        _, sync_wall = await _run_trickle(
            v_sync, singles, groups, args.gap_ms / 1000.0
        )
        await v_sync.close()
    per_size = {}
    for size in sorted(lat):
        xs = lat[size]
        sigs = size * len(xs)
        per_size[str(size)] = {
            "requests": len(xs),
            "sigs": sigs,
            "p50_ms": round(_quantile(xs, 0.5) * 1e3, 2),
            "p99_ms": round(_quantile(xs, 0.99) * 1e3, 2),
        }
    import jax

    from lodestar_tpu.utils.provenance import provenance

    pipeline: dict = {"depth": depth}
    if sync_wall:
        pipeline["sync_sigs_per_sec"] = round(
            total_sigs / sync_wall, 2
        )
        pipeline["overlap_speedup"] = round(sync_wall / wall, 4)
    return {
        "metric": "bls_trickle_gossip_shaped",
        "provenance": provenance(),
        "platform": jax.default_backend(),
        "devices": len(jax.devices()),
        "rolling_enabled": not args.no_rolling,
        "pipeline": pipeline,
        "latency_budget_ms": args.latency_budget_ms,
        "ingest_min_bucket": K.ingest_min_bucket(),
        "gap_ms": args.gap_ms,
        "total_sigs": total_sigs,
        "wall_s": round(wall, 3),
        "sigs_per_sec": round(total_sigs / wall, 2),
        "per_group_size": per_size,
        "dispatch_by_bucket": {
            str(k): c
            for k, c in sorted(m.dispatch_by_bucket.items())
        },
        "dispatch_by_path": dict(m.dispatch_by_path),
        "rolling_flushes": dict(m.rolling_flushes),
        "verifier_latency": m.verify_latency.snapshot(),
        "same_message_latency": m.same_message_latency.snapshot(),
        "caveat": (
            "real TPU attached; production trickle shape"
            if jax.default_backend() == "tpu"
            else "NO TPU in this container: CPU XLA emulates the "
            "device on one host core, so absolute sigs/s and "
            "latency measure the emulation, not the chip; the "
            "arrival shape, coalescing behavior, and counters are "
            "real. Run with --real on TPU hardware for the chip "
            "numbers."
        ),
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--real", action="store_true",
                   help="production sizes (requires an attached TPU "
                   "for meaningful numbers)")
    p.add_argument("--singles", type=int, default=24,
                   help="number of 1-set aggregate jobs in the trickle")
    p.add_argument("--group-sizes", type=int, nargs="+",
                   default=[16, 32, 128],
                   help="same-message group sizes to interleave")
    p.add_argument("--group-reps", type=int, default=2,
                   help="repetitions of each group size")
    p.add_argument("--gap-ms", type=float, default=20.0,
                   help="arrival gap between trickle items")
    p.add_argument("--latency-budget-ms", type=int, default=None,
                   help="rolling-bucket latency budget (default 50; "
                   "an explicit value wins over --autotune-from)")
    p.add_argument("--ingest-min-bucket", type=int, default=None)
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="verifier wave-overlap depth (1 = synchronous "
                   "dispatch; default: verifier default). A depth > 1 "
                   "adds a second sync run for an A/B pair")
    p.add_argument("--no-rolling", action="store_true",
                   help="disable continuous batching (A/B reference)")
    p.add_argument("--warmup", action="store_true",
                   help="block on full ingest warmup before measuring")
    p.add_argument("--autotune-from", default=None,
                   help="replay a recorded autotune decision JSON "
                   "(AUTOTUNE.json) before measuring")
    p.add_argument("--json-out", default=None)
    args = p.parse_args()
    if args.real:
        args.singles = max(args.singles, 64)
        args.group_reps = max(args.group_reps, 8)
    out = asyncio.run(_bench(args))
    line = json.dumps(out, indent=2)
    print(line)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()

"""Static limb-radix evaluation: 2^10 vs 2^12 (vs 2^13) for Fq mul.

ISSUE 7 asks whether radix-2^12 limbs (~1.5x fewer conv MACs) should
replace radix-2^10 alongside the MXU int8 backend. This tool answers
with the SAME trace-time interval machinery the runtime uses
(ops/limbs._conv_bounds / _mxu_conv_plan are radix-agnostic — they
take bound tuples), so the numbers are proofs, not estimates:

  - conv MAC counts (int32 VPU and int8 MXU, 2-slice decomposition);
  - LAZY-ADD DEPTH: the largest k such that a conv of operands that
    are sums of k canonical values still fits int32 without a
    normalize. The Karatsuba towers lean on this — fq2_mul feeds
    conv(add(a0,a1), add(b0,b1)) (depth 2) and fq6/fq12 stack more —
    so a radix whose depth collapses to <2 forces extra normalizes
    (each one a carry cascade + fold matmul) before most tower convs,
    which costs more than the MAC savings recover.

No device required; pure python. Run: python tools/eval_radix.py
"""

from __future__ import annotations

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lodestar_tpu.crypto.bls.fields import P  # noqa: E402
from lodestar_tpu.ops import limbs as L  # noqa: E402


def lazy_add_depth(nlimb: int, canon_hi: int, check) -> int:
    """Max k with conv(k-sum, k-sum) admissible under `check`."""
    k = 0
    while k < 64:
        hi = tuple([(k + 1) * canon_hi] * nlimb)
        lo = tuple([0] * nlimb)
        if not check(lo, hi, lo, hi):
            break
        k += 1
    return k


def vpu_ok(alo, ahi, blo, bhi) -> bool:
    lo, hi, absmax = L._conv_bounds(alo, ahi, blo, bhi)
    return not L._overflows(lo, hi) and absmax <= L.INT32_MAX


def mxu_ok(alo, ahi, blo, bhi) -> bool:
    return vpu_ok(alo, ahi, blo, bhi) and L._mxu_conv_plan(
        alo, ahi, blo, bhi
    )


def evaluate(bits: int) -> dict:
    b = 1 << bits
    nlimb = math.ceil(P.bit_length() / bits)
    canon_hi = b + 1  # canonical profile analog (limbs <= B+1)
    nout = 2 * nlimb - 1
    int32_macs = nlimb * nout
    # 2-slice int8 decomposition (lo7 + hi<<7); hi slice spans
    # bits-7 bits for canonical values — representable iff limb
    # magnitude < 2^15 (hi slice in int8), true for both radices.
    int8_macs = 4 * nlimb * nout
    return {
        "bits": bits,
        "nlimb": nlimb,
        "int32_macs": int32_macs,
        "int8_macs": int8_macs,
        "lazy_depth_vpu": lazy_add_depth(nlimb, canon_hi, vpu_ok),
        "lazy_depth_mxu": lazy_add_depth(nlimb, canon_hi, mxu_ok),
    }


def main() -> None:
    rows = [evaluate(b) for b in (10, 12, 13)]
    base = rows[0]
    print(
        "| radix | limbs | int32 MACs/mul | int8 MACs/mul | MAC ratio "
        "| lazy-add depth (vpu) | lazy-add depth (mxu) |"
    )
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(
            f"| 2^{r['bits']} | {r['nlimb']} | {r['int32_macs']} "
            f"| {r['int8_macs']} "
            f"| {base['int32_macs'] / r['int32_macs']:.2f}x "
            f"| {r['lazy_depth_vpu']} | {r['lazy_depth_mxu']} |"
        )
    r12 = rows[1]
    print()
    if r12["lazy_depth_vpu"] < 2 or r12["lazy_depth_mxu"] < 2:
        print(
            "VERDICT: radix-2^12 collapses the lazy-add depth below "
            "the Karatsuba towers' working depth (fq2_mul needs 2, "
            "fq6/fq12 stack deeper): nearly every tower conv would "
            "need a pre-normalize (carry cascade + fold matmul), "
            "costing more than the "
            f"{base['int32_macs'] / r12['int32_macs']:.2f}x MAC saving "
            "recovers. Radix-2^10 stays."
        )
    else:
        print(
            "VERDICT: radix-2^12 keeps enough lazy-add headroom — "
            "worth a measured prototype."
        )


if __name__ == "__main__":
    main()

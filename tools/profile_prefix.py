"""TRUE per-stage device budget of the production 2048-set pipeline.

profile_bucket.py forces each stage with a FULL-tensor device_get,
which over the tunneled backend adds hundreds of ms of transfer per
stage — fine for ranking, useless as a budget. This tool instead times
cumulative PREFIXES of the stage chain, reducing each prefix's output
to one scalar on device (a tiny extra jit) so the readback is ()-
shaped; stage cost = prefix[k] - prefix[k-1]. All heavy stages hit the
same compiled artifacts production uses.
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from lodestar_tpu.bls import kernels  # noqa: E402
from lodestar_tpu.bls import api as bls_api  # noqa: E402
from lodestar_tpu.bls.verifier import _rand_scalars  # noqa: E402
from lodestar_tpu.crypto.bls import curve as oc  # noqa: E402
from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.ops import limbs as L  # noqa: E402
from lodestar_tpu.params import BLS_DST_SIG  # noqa: E402

N = 2048
KEYS = 256

# --limb-backend {vpu,mxu}: stage-budget either limb backend through
# the same compiled artifacts (regressions between backends must be
# attributable per stage). --n M shrinks the bucket for CPU smokes.
if "--limb-backend" in sys.argv:
    L.set_backend(sys.argv[sys.argv.index("--limb-backend") + 1])
if "--n" in sys.argv:
    N = int(sys.argv[sys.argv.index("--n") + 1])
    KEYS = min(KEYS, N)


@jax.jit
def _scalarize(tree):
    """Reduce any pytree of arrays to one int32 scalar on device."""
    leaves = jax.tree.leaves(tree)
    acc = jnp.int32(0)
    for leaf in leaves:
        acc = acc + jnp.sum(leaf.astype(jnp.int32) & 0xFF)
    return acc


def timeit(label, fn, reps=3):
    out = fn()
    np.asarray(jax.device_get(out))  # warm (stages already cached)
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(jax.device_get(fn()))
    dt = (time.perf_counter() - t0) / reps
    print(f"{label}: {dt * 1000:.1f} ms", flush=True)
    return dt


def main() -> None:
    print(
        f"platform={jax.default_backend()} N={N} "
        f"limb_backend={L.get_backend()}",
        flush=True,
    )
    pks, sig_parts, draws = [], [], []
    key_pts = {}
    for i in range(N):
        sk = 10_000 + (i % KEYS)
        if sk not in key_pts:
            key_pts[sk] = oc.g1_mul(oc.G1_GEN, sk)
        msg = i.to_bytes(32, "little")
        h = hash_to_g2(msg, BLS_DST_SIG)
        pks.append(key_pts[sk])
        xc0, xc1, sgn, ok = bls_api.parse_signature(
            oc.g2_to_bytes(oc.g2_mul(h, sk))
        )
        assert ok
        sig_parts.append((xc0, xc1, sgn))
        draws.append(bls_api.message_draws(msg))

    pk = C.g1_batch_from_ints(pks)
    sig_x = (
        L.from_ints([s[0] for s in sig_parts]),
        L.from_ints([s[1] for s in sig_parts]),
    )
    sign_arr = jnp.asarray(
        np.asarray([s[2] for s in sig_parts], np.int32)
    )
    u0 = (
        L.from_ints([d[0][0] for d in draws]),
        L.from_ints([d[0][1] for d in draws]),
    )
    u1 = (
        L.from_ints([d[1][0] for d in draws]),
        L.from_ints([d[1][1] for d in draws]),
    )
    mask = jnp.ones(N, bool)
    bits = C.scalars_to_bits(_rand_scalars(N), kernels.RAND_BITS)

    K = kernels

    def p1():
        return _scalarize(K._stage_g2_sqrt(sig_x, sign_arr))

    def p2():
        x, y, qr = K._stage_g2_sqrt(sig_x, sign_arr)
        return _scalarize(K._stage_g2_subgroup(x, y, qr, mask))

    def p3():
        x, y, qr = K._stage_g2_sqrt(sig_x, sign_arr)
        sig, av = K._stage_g2_subgroup(x, y, qr, mask)
        return _scalarize((av, K._stage_sswu_iso(u0, u1)))

    def p4():
        x, y, qr = K._stage_g2_sqrt(sig_x, sign_arr)
        sig, av = K._stage_g2_subgroup(x, y, qr, mask)
        iso = K._stage_sswu_iso(u0, u1)
        return _scalarize((av, K._stage_cofactor(iso, mask)))

    def p5():
        x, y, qr = K._stage_g2_sqrt(sig_x, sign_arr)
        sig, av = K._stage_g2_subgroup(x, y, qr, mask)
        iso = K._stage_sswu_iso(u0, u1)
        hx, hy = K._stage_cofactor(iso, mask)
        return _scalarize(
            (av, K._stage_prepare_batch(pk, hx, hy, sig, bits, mask))
        )

    def p6():
        x, y, qr = K._stage_g2_sqrt(sig_x, sign_arr)
        sig, av = K._stage_g2_subgroup(x, y, qr, mask)
        iso = K._stage_sswu_iso(u0, u1)
        hx, hy = K._stage_cofactor(iso, mask)
        px, py, qx, qy, fm = K._stage_prepare_batch(
            pk, hx, hy, sig, bits, mask
        )
        return _scalarize((av, K._stage_miller(px, py, qx, qy)))

    def p7():
        x, y, qr = K._stage_g2_sqrt(sig_x, sign_arr)
        sig, av = K._stage_g2_subgroup(x, y, qr, mask)
        iso = K._stage_sswu_iso(u0, u1)
        hx, hy = K._stage_cofactor(iso, mask)
        px, py, qx, qy, fm = K._stage_prepare_batch(
            pk, hx, hy, sig, bits, mask
        )
        f = K._stage_miller(px, py, qx, qy)
        return _scalarize((av, K._stage_product(f, fm)))

    def p8():
        return K.run_verify_batch_ingest_async(
            pk, sig_x, sign_arr, u0, u1, bits, mask
        )

    labels = [
        "sqrt", "subgroup", "sswu+iso", "cofactor", "prepare",
        "miller", "product", "final(FULL)",
    ]
    prefixes = [p1, p2, p3, p4, p5, p6, p7, p8]
    times = []
    for lbl, fn in zip(labels, prefixes):
        times.append(timeit(f"prefix..{lbl}", fn))
    print("\n-- per-stage (differences) --", flush=True)
    prev = 0.0
    for lbl, tt in zip(labels, times):
        print(f"{lbl}: {(tt - prev) * 1000:.1f} ms", flush=True)
        prev = tt
    print(
        f"TOTAL {times[-1] * 1000:.1f} ms  "
        f"-> {N / times[-1]:.0f} sets/s device ceiling",
        flush=True,
    )


if __name__ == "__main__":
    main()



#!/usr/bin/env python
"""The first TPU-attached measurement round, in one command.

Every perf lever since round 6 — the MXU int8 limb backend, the mid
bucket-ladder rungs, continuous batching, mesh sharding, and now the
device auto-tuner — was built and CI-guarded on a CPU-only container;
COVERAGE.md states plainly which numbers measure the 1-core emulation
instead of the chip. This tool is the payoff script for the first
round that runs WITH hardware: it executes the whole campaign in
dependency order and leaves one artifact per step, so the post-MXU
stage budget and the chip-scaling curve land in a single run.

Steps (see REAL_CAMPAIGN.md for the runbook):

  1. preflight      — platform/device/persistent-cache check
  2. autotune       — DeviceAutotuner startup tune on the real chip
                      (full grid, generous budget) -> AUTOTUNE_real.json
  3. bench          — bench.py --autotune-from (headline sets/s under
                      the tuned config) -> BENCH_real.json
  4. pipeline       — bench.py --pipeline-depth 1,2,4 (overlapped
                      wave pipeline depth sweep: sync vs double/
                      quad buffering) -> BENCH_pipeline_real.json
  5. stage_budget   — tools/profile_prefix.py per backend: the
                      post-MXU per-stage budget that updates
                      COVERAGE.md's table -> STAGE_BUDGET_real.json
  6. trickle        — tools/bench_trickle.py --real --autotune-from
                      (gossip-shaped steady state) -> BENCH_trickle_real.json
  7. blobs          — tools/bench_blobs.py --real --autotune-from
                      (peak-DA KZG batch verify through the device
                      Pippenger MSM) -> BENCH_blobs_real.json
  8. mesh           — tools/bench_mesh_sweep.py --real --autotune-from
                      (the chip-scaling curve) -> MULTICHIP_real.json
  9. executor_contention
                    — gossip trickle (deadline class) through the
                      node DeviceExecutor while a KZG blob firehose
                      saturates the bulk lane: deadline p50/p99 with
                      vs without contention, bulk sheds, deferral
                      counts -> EXECUTOR_CONTENTION_real.json
  10. fault_drill   — the device fault domain end to end: injected
                      wave hang -> watchdog trip -> quarantine ->
                      bit-identical host failover -> probe
                      reinstatement (the device_loss_under_load
                      scenario, full profile, per-SLO verdicts)
                      -> FAULT_DRILL_real.json
  11. serving_flood — the serving fault domain under a light-client
                      read flood (tools/bench_flood.py): duty p99
                      vs quiet baseline, typed 429/503 sheds on the
                      cheap classes, Retry-After compliance, cache
                      hit ratio — machine-evaluated checks
                      -> BENCH_flood_real.json

`--dry-run` emits the full campaign plan (commands, artifacts,
prerequisites) as JSON without executing anything — reviewable on
this CPU container, runnable on the TPU host. `--steps` selects a
subset; a failed step aborts the remainder (later steps consume
earlier artifacts). The `_real` artifact suffix is reserved for
TPU-attached runs: off-TPU (`--allow-cpu` smoke) every step writes
`*_cpu.json` instead, so an emulation rehearsal can never be
mistaken for the hardware measurement.

Usage:
  python tools/run_real_campaign.py --dry-run
  python tools/run_real_campaign.py                 # on the TPU host
  python tools/run_real_campaign.py --steps autotune,bench
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PY = sys.executable

_SUFFIX: str | None = None


def artifact_suffix() -> str:
    """`real` on a TPU-attached host, `cpu` anywhere else. The
    `_real` artifact suffix is a provenance claim — these numbers
    measured hardware — so an `--allow-cpu` smoke run must never be
    able to produce a `*_real.json` file: a CPU-emulation drill
    committed under the real-campaign name records a robustness
    guarantee as demonstrated when it was only rehearsed (the exact
    emulation-vs-chip confusion COVERAGE.md exists to prevent)."""
    global _SUFFIX
    if _SUFFIX is None:
        try:
            import jax

            _SUFFIX = "real" if jax.default_backend() == "tpu" else "cpu"
        except Exception:
            _SUFFIX = "cpu"
    return _SUFFIX


def build_plan(args) -> list[dict]:
    """The campaign as data: each step is {name, why, cmd | fn,
    artifact, needs}. Commands are plain argv lists so the dry-run
    plan is copy-pasteable. Artifact names carry artifact_suffix():
    on the TPU host this plan writes the *_real.json files the
    runbook commits; off-TPU every name degrades to *_cpu.json."""
    sfx = artifact_suffix()
    at = args.autotune_artifact or f"AUTOTUNE_{sfx}.json"
    args.autotune_artifact = at
    return [
        {
            "name": "preflight",
            "why": "fail fast off-TPU; confirm the persistent "
            "compile cache is writable (a cold cache turns every "
            "later step into a multi-minute compile festival)",
            "fn": "preflight",
            "artifact": None,
            "needs": [],
        },
        {
            "name": "autotune",
            "why": "derive THIS host's config: limb backend x ingest "
            "gate x ladder top x latency budget, measured on the "
            "real chip at real ladder rungs (batch-flat cost makes "
            "the probes exact there)",
            "fn": "autotune",
            "artifact": at,
            "needs": ["preflight"],
        },
        {
            "name": "bench",
            "why": "the headline production-path sets/s under the "
            "tuned config (the number COVERAGE.md's 'Measured "
            "performance' table tracks; 10x north star ~22,200)",
            "cmd": [PY, "bench.py", "--autotune-from", at],
            "stdout": f"BENCH_{sfx}.json",
            "artifact": f"BENCH_{sfx}.json",
            "needs": ["autotune"],
        },
        {
            "name": "pipeline",
            "why": "overlapped-pipeline depth sweep on the chip: how "
            "much host prep the double-buffered dispatch (depth 2) "
            "actually hides behind device waves vs synchronous "
            "depth 1, and whether depth 4 buys anything beyond it — "
            "the seam BENCH_pipeline.json could only emulate on CPU",
            "cmd": [
                PY,
                "bench.py",
                "--autotune-from",
                at,
                "--pipeline-depth",
                "1,2,4",
            ],
            "stdout": f"BENCH_pipeline_{sfx}.json",
            "artifact": f"BENCH_pipeline_{sfx}.json",
            "needs": ["autotune"],
        },
        {
            "name": "stage_budget",
            "why": "the post-MXU per-stage device budget: the offline "
            "counterpart of the live lodestar_jax_stage_device_"
            "seconds histograms and the drift monitor's shares — "
            "updates COVERAGE.md's stage-budget table",
            "cmd": [
                PY,
                "tools/profile_prefix.py",
                "--limb-backend",
                "mxu",
                "--n",
                "2048",
            ],
            "stdout": f"STAGE_BUDGET_{sfx}.txt",
            "artifact": f"STAGE_BUDGET_{sfx}.txt",
            "needs": ["autotune"],
        },
        {
            "name": "trickle",
            "why": "gossip-shaped steady state on the chip: proves "
            "the 50ms-budget rolling bucket coalesces real arrival "
            "gaps onto the device-ingest path (BENCH_trickle's CPU "
            "caveat finally retired)",
            "cmd": [
                PY,
                "tools/bench_trickle.py",
                "--real",
                "--autotune-from",
                at,
                "--json-out",
                f"BENCH_trickle_{sfx}.json",
            ],
            "artifact": f"BENCH_trickle_{sfx}.json",
            "needs": ["autotune"],
        },
        {
            "name": "blobs",
            "why": "the DA chip curve next to the BLS one: peak "
            "max-blobs-per-block KZG batch verification through the "
            "device Pippenger MSM (ops/msm.py) under the tuned "
            "msm_window — the second workload sharing the chip, "
            "never yet measured on hardware",
            "cmd": [
                PY,
                "tools/bench_blobs.py",
                "--real",
                "--backend",
                "auto",
                "--autotune-from",
                at,
                "--json-out",
                f"BENCH_blobs_{sfx}.json",
            ],
            "artifact": f"BENCH_blobs_{sfx}.json",
            "needs": ["autotune"],
        },
        {
            "name": "mesh",
            "why": "the chip-scaling curve (strong scaling over the "
            "attached chips) — the multi-chip arm of the 10x path, "
            "never yet measured on hardware (MULTICHIP_SWEEP.json "
            "is virtual devices)",
            "cmd": [
                PY,
                "tools/bench_mesh_sweep.py",
                "--real",
                "--autotune-from",
                at,
                "--sets",
                "2048",
                "--reps",
                "3",
                "--json-out",
                f"MULTICHIP_{sfx}.json",
            ],
            "artifact": f"MULTICHIP_{sfx}.json",
            "needs": ["autotune"],
        },
        {
            "name": "executor_contention",
            "why": "the QoS guarantee under real contention: gossip "
            "verdict latency (deadline class) while a blob KZG "
            "firehose saturates the executor's bulk lane — deadline "
            "p99 should hold near its quiet baseline (~one wave), "
            "with the pressure showing up as bulk sheds and "
            "deferrals instead (device/executor.py)",
            "fn": "executor_contention",
            "artifact": f"EXECUTOR_CONTENTION_{sfx}.json",
            "needs": ["autotune"],
        },
        {
            "name": "fault_drill",
            "why": "the robustness guarantee next to the perf "
            "numbers: a hung device mid-wave must cost the node its "
            "speed-up, never its correctness — wave-watchdog trip -> "
            "quarantine -> bit-identical host failover -> autotuner "
            "frozen -> probe reinstatement, each an SLO row "
            "(device/health.py; scenario device_loss_under_load)",
            "fn": "fault_drill",
            "artifact": f"FAULT_DRILL_{sfx}.json",
            "needs": ["preflight"],
        },
        {
            "name": "serving_flood",
            "why": "the serving-tier robustness guarantee next to "
            "the device one: a light-client read flood against the "
            "REST tier must shed typed 429/503s on the cheap QoS "
            "classes (Retry-After on every refusal, zero 500s) while "
            "duty p99 holds within 2x quiet and the head-keyed "
            "cache absorbs the hot reads (api/overload.py; the "
            "bench's checks are machine-evaluated and a failed "
            "check fails the step)",
            "cmd": [
                PY,
                "tools/bench_flood.py",
                "--json-out",
                f"BENCH_flood_{sfx}.json",
            ],
            "artifact": f"BENCH_flood_{sfx}.json",
            "needs": ["preflight"],
        },
    ]


def step_preflight(args) -> dict:
    import jax

    from lodestar_tpu.utils import jaxcache
    from lodestar_tpu.utils.provenance import provenance

    jaxcache.enable()
    platform = jax.default_backend()
    devs = jax.devices()
    info = {
        "platform": platform,
        "devices": len(devs),
        "device_kind": str(getattr(devs[0], "device_kind", "")),
        "provenance": provenance(),
    }
    if platform != "tpu" and not args.allow_cpu:
        raise SystemExit(
            f"preflight: platform is {platform!r}, not 'tpu'. This "
            "campaign measures hardware; run it on the TPU host "
            "(--allow-cpu to force a smoke run whose numbers are "
            "emulation, not measurement)."
        )
    return info


def step_autotune(args) -> dict:
    from lodestar_tpu.device.autotune import DeviceAutotuner

    tuner = DeviceAutotuner(
        budget_ms=args.autotune_budget_ms,
        # anchored to the repo: the later subprocess steps resolve
        # the artifact against REPO (cwd=REPO), and so does the
        # resume check — a cwd-relative write from $HOME would strand
        # the expensive tune's output where nothing reads it
        artifact_path=os.path.join(REPO, args.autotune_artifact),
        mode="startup",
    )
    return tuner.tune(trigger="campaign")


def step_executor_contention(args) -> dict:
    """Deadline QoS under bulk pressure, measured in-process: the
    same gossip trickle runs twice through a node DeviceExecutor —
    once quiet, once with a KZG blob-batch firehose hammering the
    bulk lane from a second thread — and the artifact records the
    caller-observed verdict p50/p99 of both phases next to the
    executor's own accounting (bulk throughput, sheds, deadline
    deferrals). The acceptance shape: contended deadline p99 holds
    near the quiet baseline, and the pressure is visible as
    bulk-class sheds/deferrals instead of verdict latency."""
    import asyncio
    import threading

    from lodestar_tpu.bls import TpuBlsVerifier
    from lodestar_tpu.crypto import kzg
    from lodestar_tpu.device import autotune as at
    from lodestar_tpu.device.executor import DeviceExecutor
    from lodestar_tpu.utils import jaxcache
    from lodestar_tpu.utils.provenance import provenance

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_blobs as BB
    import bench_trickle as BT

    jaxcache.enable()
    dec_path = os.path.join(REPO, args.autotune_artifact)
    if os.path.exists(dec_path):
        with open(dec_path) as f:
            at.apply_decision(json.load(f))

    kzg.activate_trusted_setup(kzg.dev_trusted_setup())
    blobs, comms, proofs = BB.build_batch(args.contention_blobs)
    gap_s = args.contention_gap_ms / 1000.0

    async def phase(firehose: bool) -> dict:
        ex = DeviceExecutor()
        kzg.set_executor(ex)
        v = TpuBlsVerifier()
        v.attach_executor(ex)
        singles = BT._build_single_sets(args.contention_sets)
        stop = threading.Event()
        fired = {"batches": 0}

        def pump():
            # the bulk client: blob batches back to back; each MSM
            # rides the executor's bulk lane (or sheds to the host
            # tiers when admission control says no — also the point)
            while not stop.is_set():
                kzg.verify_blob_kzg_proof_batch(blobs, comms, proofs)
                fired["batches"] += 1

        th = None
        if firehose:
            th = threading.Thread(
                target=pump, name="blob-firehose", daemon=True
            )
            th.start()
        try:
            lat, wall = await BT._run_trickle(v, singles, [], gap_s)
        finally:
            stop.set()
            if th is not None:
                th.join(timeout=30.0)
            await v.close()
            kzg.set_executor(None)
            ex.close()
        xs = lat.get(1, [])
        return {
            "firehose": firehose,
            "gossip_jobs": len(xs),
            "deadline_p50_ms": BT._quantile(xs, 0.50) * 1000.0,
            "deadline_p99_ms": BT._quantile(xs, 0.99) * 1000.0,
            "wall_s": wall,
            "bulk_batches": fired["batches"],
            "bulk_blobs_per_batch": args.contention_blobs,
            "deadline_deferrals": ex.deadline_deferrals,
            "executor_sheds": {
                f"{cls}/{reason}": n
                for (cls, reason), n in sorted(
                    ex.shed_counts().items()
                )
            },
            "msm_paths": kzg.msm_path_counts(),
        }

    quiet = asyncio.run(phase(firehose=False))
    contended = asyncio.run(phase(firehose=True))
    out = {
        "workload": "gossip trickle (deadline) vs blob KZG firehose "
        "(bulk) through one DeviceExecutor",
        "quiet": quiet,
        "contended": contended,
        "provenance": provenance(),
    }
    with open(
        os.path.join(
            REPO, f"EXECUTOR_CONTENTION_{artifact_suffix()}.json"
        ),
        "w",
    ) as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    return out


def step_fault_drill(args) -> dict:
    """The device fault domain exercised end to end: the
    device_loss_under_load scenario at the full profile — an injected
    mid-wave hang trips the wave watchdog, quarantines the device,
    fails the remaining buckets over to the host path (verdicts
    bit-identical), freezes the autotuner, then reinstates via
    known-answer probes — with every guarantee an explicit SLO row in
    the artifact. Deterministic (injected faults + manual breaker
    clock), so the same drill gates tier-1 on CPU; on the TPU host it
    proves the failover seams against the real dispatch stack and
    writes FAULT_DRILL_real.json. Off-TPU (--allow-cpu) the identical
    drill is a rehearsal against the emulated dispatch stack and
    writes FAULT_DRILL_cpu.json — never the real-campaign name. A
    failed SLO row fails the step (and so the campaign)."""
    from lodestar_tpu.sim.scenarios import run_scenario
    from lodestar_tpu.utils.provenance import provenance

    res = run_scenario(
        "device_loss_under_load", profile="full", seed=args.drill_seed
    )
    out = dict(res.to_dict())
    out["provenance"] = provenance()
    artifact = f"FAULT_DRILL_{artifact_suffix()}.json"
    with open(os.path.join(REPO, artifact), "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    if res.error:
        raise RuntimeError(
            f"fault drill crashed:\n{res.error}"
        )
    failed = [s.name for s in res.slos if not s.passed]
    if failed:
        raise RuntimeError(
            f"fault drill SLO rows failed: {failed} "
            f"(see {artifact})"
        )
    return out


def run(args) -> int:
    plan = build_plan(args)
    want = (
        [s.strip() for s in args.steps.split(",") if s.strip()]
        if args.steps
        else [st["name"] for st in plan]
    )
    unknown = set(want) - {st["name"] for st in plan}
    if unknown:
        print(f"unknown steps: {sorted(unknown)}", file=sys.stderr)
        return 2
    if args.dry_run:
        out = {
            "campaign": "first TPU-attached measurement round",
            "runbook": "REAL_CAMPAIGN.md",
            "cwd": REPO,
            "platform": (
                "tpu" if artifact_suffix() == "real" else "cpu"
            ),
            "artifact_suffix": artifact_suffix(),
            "note": "artifact names reflect THIS host: on the TPU "
            "host they are *_real.json; off-TPU every step writes "
            "*_cpu.json so an emulation run can never masquerade as "
            "a hardware measurement",
            "steps": [
                {
                    "name": st["name"],
                    "selected": st["name"] in want,
                    "why": st["why"],
                    "command": (
                        " ".join(st["cmd"])
                        + (
                            f" > {st['stdout']}"
                            if st.get("stdout")
                            else ""
                        )
                        if "cmd" in st
                        else f"<in-process: {st['fn']}>"
                    ),
                    "artifact": st["artifact"],
                    "needs": st["needs"],
                }
                for st in plan
            ],
        }
        print(json.dumps(out, indent=2))
        return 0
    done: set[str] = set()
    results: dict = {}
    fns = {
        "preflight": step_preflight,
        "autotune": step_autotune,
        "executor_contention": step_executor_contention,
        "fault_drill": step_fault_drill,
    }
    for st in plan:
        if st["name"] not in want:
            continue
        missing = [n for n in st["needs"] if n not in done]
        if missing:
            # a skipped prerequisite is fine when its artifact
            # already exists on disk (resuming a campaign). A
            # prerequisite WITHOUT an artifact (preflight) can only
            # be satisfied by running it: letting `--steps
            # fault_drill` skip preflight is how a CPU run once
            # produced an artifact under the real-campaign name.
            for n in missing:
                art = next(
                    p["artifact"] for p in plan if p["name"] == n
                )
                if art is None:
                    print(
                        f"step {st['name']}: prerequisite {n} "
                        "leaves no artifact and must run in this "
                        f"invocation — use --steps {n},{st['name']}",
                        file=sys.stderr,
                    )
                    return 1
                if not os.path.exists(os.path.join(REPO, art)):
                    print(
                        f"step {st['name']}: prerequisite {n} not "
                        f"run and artifact {art} absent",
                        file=sys.stderr,
                    )
                    return 1
        print(f"==> {st['name']}", file=sys.stderr)
        try:
            if "fn" in st:
                results[st["name"]] = fns[st["fn"]](args)
            elif st.get("stdout"):
                with open(os.path.join(REPO, st["stdout"]), "w") as f:
                    subprocess.run(
                        st["cmd"], cwd=REPO, check=True, stdout=f
                    )
            else:
                subprocess.run(st["cmd"], cwd=REPO, check=True)
        except Exception as e:
            print(
                f"step {st['name']} FAILED: {e!r} — aborting the "
                "remainder (later steps consume earlier artifacts)",
                file=sys.stderr,
            )
            return 1
        done.add(st["name"])
    print(
        json.dumps(
            {
                "completed": sorted(done),
                "artifacts": [
                    st["artifact"]
                    for st in plan
                    if st["name"] in done and st["artifact"]
                ],
            },
            indent=2,
        )
    )
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--dry-run",
        action="store_true",
        help="emit the campaign plan as JSON without executing",
    )
    p.add_argument(
        "--steps",
        default=None,
        help="comma-separated subset of steps to run",
    )
    p.add_argument(
        "--autotune-budget-ms",
        type=float,
        default=1_200_000.0,
        help="tune budget on the real chip (default 20 min: first "
        "run pays real compiles; repeats ride the persistent cache)",
    )
    p.add_argument(
        "--autotune-artifact",
        default=None,
        help="tune-decision artifact name (default AUTOTUNE_real"
        ".json on the TPU host, AUTOTUNE_cpu.json elsewhere)",
    )
    p.add_argument(
        "--contention-sets",
        type=int,
        default=128,
        help="gossip jobs per executor-contention phase",
    )
    p.add_argument(
        "--contention-blobs",
        type=int,
        default=6,
        help="blobs per firehose batch in the executor-contention "
        "step (6 = max blobs per block)",
    )
    p.add_argument(
        "--contention-gap-ms",
        type=float,
        default=20.0,
        help="gossip arrival gap in the executor-contention step",
    )
    p.add_argument(
        "--drill-seed",
        type=int,
        default=20260807,
        help="scenario seed for the fault_drill step (matches the "
        "scenario fleet's default; the drill is deterministic, so "
        "one seed reproduces one transcript)",
    )
    p.add_argument(
        "--allow-cpu",
        action="store_true",
        help="let preflight pass off-TPU (smoke only; numbers "
        "measure the CPU emulation)",
    )
    return run(p.parse_args())


if __name__ == "__main__":
    sys.exit(main())

"""Scale-realism benchmarks at mainnet preset (VERDICT r2 #10).

Reference analog: packages/state-transition/test/perf/ (epoch
processing per step, hashTreeRoot, block packing). Measures, at
100k-1M validator registries:
  - full epoch transition (process_epoch) on a participation-filled
    altair state,
  - aggregated-attestation pool packing (getAttestationsForBlock),
  - swap-or-not shuffling of the full registry.
HTR numbers live in tools/bench_htr.py.

Run: LODESTAR_PRESET=mainnet python tools/bench_scale.py [n_validators]
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("LODESTAR_PRESET", "mainnet")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from lodestar_tpu.chain.oppools import AggregatedAttestationPool  # noqa: E402
from lodestar_tpu.config.chain_config import ChainConfig  # noqa: E402
from lodestar_tpu.params import preset  # noqa: E402
from lodestar_tpu.statetransition import util  # noqa: E402
from lodestar_tpu.statetransition.epoch import process_epoch  # noqa: E402
from lodestar_tpu.types import factory  # noqa: E402

FAR = 2**64 - 1


def build_state(types, n: int):
    """Active altair registry of n validators with full participation
    (the worst-case epoch-processing shape)."""
    ns = types.by_fork["altair"]
    state = ns.BeaconState.default()
    p = preset()
    state.slot = 10 * p.SLOTS_PER_EPOCH - 1
    for i in range(n):
        state.validators.append(
            types.Validator(
                pubkey=i.to_bytes(48, "little"),
                withdrawal_credentials=(i * 7).to_bytes(32, "little"),
                effective_balance=32_000_000_000,
                slashed=False,
                activation_eligibility_epoch=0,
                activation_epoch=0,
                exit_epoch=FAR,
                withdrawable_epoch=FAR,
            )
        )
        state.balances.append(32_000_000_000)
    state.previous_epoch_participation = [0b111] * n
    state.current_epoch_participation = [0b111] * n
    state.inactivity_scores = [0] * n
    # checkpoints so justification math runs
    state.current_justified_checkpoint.epoch = 8
    state.previous_justified_checkpoint.epoch = 8
    state.finalized_checkpoint.epoch = 7
    for i in range(p.EPOCHS_PER_HISTORICAL_VECTOR):
        state.randao_mixes[i] = os.urandom(32)
    for i in range(p.SLOTS_PER_HISTORICAL_ROOT):
        state.block_roots[i] = b"\x11" * 32
        state.state_roots[i] = b"\x22" * 32
    return state


def bench_epoch(cfg, types, n: int) -> float:
    from lodestar_tpu.params import ForkSeq

    state = build_state(types, n)
    t0 = time.perf_counter()
    process_epoch(cfg, state, types, int(ForkSeq.altair))
    return time.perf_counter() - t0


def bench_shuffle(types, n: int) -> float:
    state = build_state(types, n)
    t0 = time.perf_counter()
    util.get_shuffling(state, 9)
    return time.perf_counter() - t0


def bench_pool_packing(types, n_atts: int = 1024) -> float:
    """Pack a slot's block attestations from a pool holding n_atts
    aggregates across recent slots (aggregatedAttestationPool.ts:94)."""
    p = preset()
    pool = AggregatedAttestationPool(types)
    comm = p.TARGET_COMMITTEE_SIZE
    for i in range(n_atts):
        att = types.Attestation.default()
        att.data.slot = 30 + (i % p.SLOTS_PER_EPOCH)
        att.data.index = i % p.MAX_COMMITTEES_PER_SLOT
        att.data.beacon_block_root = bytes([i % 251]) * 32
        att.aggregation_bits = [
            (i + j) % 3 != 0 for j in range(comm)
        ]
        att.signature = b"\xc0" + b"\x00" * 95
        pool.add(att)
    t0 = time.perf_counter()
    got = pool.get_attestations_for_block(30 + p.SLOTS_PER_EPOCH)
    dt = time.perf_counter() - t0
    assert len(got) <= p.MAX_ATTESTATIONS
    return dt


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    cfg = ChainConfig(ALTAIR_FORK_EPOCH=0)
    types = factory.ssz_types()
    p = preset()
    print(f"preset={os.environ['LODESTAR_PRESET']} validators={n}")
    dt = bench_epoch(cfg, types, n)
    print(f"epoch transition ({n} validators): {dt * 1000:.0f} ms")
    dt = bench_shuffle(types, n)
    print(f"shuffling ({n} validators): {dt * 1000:.0f} ms")
    dt = bench_pool_packing(types)
    print(f"attestation pool packing (1024 aggregates): {dt * 1000:.1f} ms")


if __name__ == "__main__":
    main()

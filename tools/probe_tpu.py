"""Probe per-stage compile+run times of the BLS pipeline on the default
JAX platform (the tunneled TPU under axon). Diagnoses bench stalls."""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from lodestar_tpu.utils import jaxcache  # noqa: E402

jaxcache.enable()

from lodestar_tpu.crypto.bls import curve as oc  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.ops import fq, pairing, tower  # noqa: E402
from lodestar_tpu.ops import limbs as L  # noqa: E402


def t(label, fn):
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    t1 = time.perf_counter()
    out2 = fn()
    jax.block_until_ready(out2)
    t2 = time.perf_counter()
    print(
        f"{label}: compile+run {t1 - t0:.2f}s, steady {t2 - t1:.4f}s",
        flush=True,
    )
    return out


def main() -> None:
    print(f"platform={jax.default_backend()}", flush=True)
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 128

    a = L.from_ints([3 + i for i in range(n)])
    b = L.from_ints([5 + i for i in range(n)])
    t("fq.mul batch", jax.jit(fq.mul).lower(a, b).compile if False else lambda: jax.jit(fq.mul)(a, b))

    # G1 scalar ladder, 64-bit, batch n
    pks = [oc.g1_mul(oc.G1_GEN, 1000 + i) for i in range(n)]
    pk = C.g1_batch_from_ints(pks)
    bits = C.scalars_to_bits([(0x9E37 + i) | 1 for i in range(n)], 64)
    f = jax.jit(lambda x, y, bb, i: C.scalar_mul(C.FQ_OPS, x, y, bb, i))
    t("g1 scalar_mul x64 ladder", lambda: f(pk.x, pk.y, bits, pk.inf))

    # G2 scalar ladder
    hs = [oc.g2_mul(oc.G2_GEN, 7 + i) for i in range(n)]
    h = C.g2_batch_from_ints(hs)
    f2 = jax.jit(lambda x, y, bb, i: C.scalar_mul(C.FQ2_OPS, x, y, bb, i))
    t("g2 scalar_mul x64 ladder", lambda: f2(h.x, h.y, bits, h.inf))

    # jac_sum tree over n G2 points
    fsum = jax.jit(lambda p: C.jac_sum(C.FQ2_OPS, p))
    t("g2 jac_sum tree", lambda: fsum(h))

    # fq inversion (Fermat)
    t("fq.inv", lambda: jax.jit(fq.inv)(a))

    # miller loop batch n
    px = L.from_ints([p[0] for p in pks])
    py = L.from_ints([p[1] for p in pks])
    qx = tower.fq2_from_ints([p[0] for p in hs])
    qy = tower.fq2_from_ints([p[1] for p in hs])
    fm = jax.jit(pairing.miller_loop)
    fout = t("miller_loop", lambda: fm(px, py, qx, qy))

    # masked product + final exp
    mask = jnp.ones((n,), jnp.bool_)
    fp = jax.jit(
        lambda ff, m: pairing.fq12_is_one(
            pairing.final_exponentiation(pairing._fq12_masked_product(ff, m))
        )
    )
    t("product+final_exp", lambda: fp(fout, mask))


if __name__ == "__main__":
    main()

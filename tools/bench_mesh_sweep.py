"""Multi-chip batch-axis scaling sweep: sets/s vs device count.

COVERAGE.md's mesh-scaling claim must be backed by a measurement, not
an assertion (VERDICT r3+): this tool runs the SAME batch-verify
kernels production uses (bls/kernels.run_verify_batch) with the
signature batch axis sharded over a 1/2/4/8-device mesh
(lodestar_tpu/parallel) and reports sets/s + parallel efficiency per
device count.

Modes:
  parent (default): re-execs itself once per device count with
    JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count=D when
    the host has fewer than D real devices (the same dance as
    __graft_entry__.dryrun_multichip — the flags must be set before
    jax import). With >= D real TPU chips the child inherits them and
    the numbers are real scaling; on the CPU fallback the curve
    validates sharding correctness and collective lowering, not
    absolute throughput (one host core executes all virtual devices).
  child (--child): builds n valid sets, shards them over a D-device
    mesh, warms the compile, times reps of the full verify pipeline,
    prints one JSON line.

The workload is FIXED across device counts (strong scaling): the same
n sets are split D ways, so ideal scaling is rate_D == D * rate_1 and
efficiency = rate_D / (D * rate_1).

tests/test_mesh_sweep.py smoke-runs run_workload() on the 8-virtual-
device tier-1 mesh so mesh-sharding breakage is caught by `-m 'not
slow'`, not only by TPU runs.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_COUNTS = (1, 2, 4, 8)


def build_inputs(n: int):
    """n valid (pk, H(msg), sig) sets as device batches + rand bits.
    Small scalars keep fixture cost low; verify cost is scalar-blind."""
    import jax.numpy as jnp

    from lodestar_tpu.bls import kernels
    from lodestar_tpu.crypto.bls import curve as oc
    from lodestar_tpu.ops import curve as C

    hs = [oc.g2_mul(oc.G2_GEN, 7 + i) for i in range(n)]
    pks, sigs = [], []
    for i, h in enumerate(hs):
        sk = 100 + i
        pks.append(oc.g1_mul(oc.G1_GEN, sk))
        sigs.append(oc.g2_mul(h, sk))
    pk_dev = C.g1_batch_from_ints(pks)
    h_pt = C.g2_batch_from_ints(hs)
    h_dev = (h_pt.x, h_pt.y)  # affine coords, as the verifier passes h
    sig_dev = C.g2_batch_from_ints(sigs)
    rand = [
        ((0x9E3779B97F4A7C15 ^ (i * 0x5851F42D4C957F2D)) & (2**64 - 1)) | 1
        for i in range(n)
    ]
    bits = C.scalars_to_bits(rand, kernels.RAND_BITS)
    mask = jnp.ones(n, bool)
    return pk_dev, h_dev, sig_dev, bits, mask


def run_workload(n_devices: int, n_sets: int, reps: int = 1):
    """Verify n_sets sharded over an n_devices mesh; returns
    (sets_per_sec, all_valid). Compile excluded (one warmup rep).
    reps=0 is smoke mode: only the warmup correctness run executes
    and the rate is reported as 0.0."""
    import jax

    from lodestar_tpu import parallel
    from lodestar_tpu.bls import kernels

    assert n_sets % n_devices == 0, "batch axis must divide the mesh"
    mesh = parallel.make_mesh(n_devices)
    pk_dev, h_dev, sig_dev, bits, mask = build_inputs(n_sets)
    pk_dev = parallel.shard_batch(mesh, pk_dev)
    h_dev = parallel.shard_batch(mesh, h_dev)
    sig_dev = parallel.shard_batch(mesh, sig_dev)
    bits = parallel.shard_batch(mesh, bits)
    mask = parallel.shard_batch(mesh, mask)

    def once() -> bool:
        return bool(
            jax.device_get(
                kernels.run_verify_batch_async(
                    pk_dev, h_dev, sig_dev, bits, mask
                )
            )
        )

    ok = once()  # warmup: compile + correctness gate
    if reps == 0:
        return 0.0, ok
    t0 = time.perf_counter()
    for _ in range(reps):
        ok = once() and ok
    dt = time.perf_counter() - t0
    return n_sets * reps / dt, ok


def _child(args) -> None:
    import jax

    from lodestar_tpu.ops import limbs as L

    if args.autotune_from:
        # replay the recorded decision in EVERY child: the sweep then
        # measures the tuner's backend/ladder at each mesh size
        from lodestar_tpu.device import autotune as AT

        AT.apply_decision(AT.load_decision(args.autotune_from))
    if args.limb_backend:
        L.set_backend(args.limb_backend)
    rate, ok = run_workload(args.devices, args.sets, args.reps)
    print(
        json.dumps(
            {
                "devices": args.devices,
                "sets": args.sets,
                "reps": args.reps,
                "platform": jax.default_backend(),
                "limb_backend": L.get_backend(),
                "sets_per_sec": round(rate, 2),
                "ok": ok,
            }
        )
    )


def _spawn(d: int, args) -> dict:
    env = dict(os.environ)
    # scrub accelerator bindings unless the host really has d devices
    # (same scrub list as __graft_entry__.dryrun_multichip)
    if not args.real:
        for k in list(env):
            if k.startswith(
                ("TPU_", "PJRT_", "LIBTPU", "AXON_", "PALLAS_AXON")
            ):
                env.pop(k)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={d}"
        ).strip()
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--child",
        "--devices",
        str(d),
        "--sets",
        str(args.sets),
        "--reps",
        str(args.reps),
    ]
    if args.limb_backend:
        cmd += ["--limb-backend", args.limb_backend]
    if args.autotune_from:
        cmd += ["--autotune-from", os.path.abspath(args.autotune_from)]
    res = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=3600
    )
    if res.returncode != 0:
        raise RuntimeError(
            f"sweep child (devices={d}) failed:\n{res.stdout[-2000:]}\n"
            f"{res.stderr[-4000:]}"
        )
    return json.loads(res.stdout.strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument(
        "--counts",
        default=",".join(map(str, DEFAULT_COUNTS)),
        help="device counts to sweep (parent mode)",
    )
    ap.add_argument("--sets", type=int, default=64)
    ap.add_argument("--reps", type=int, default=1)
    ap.add_argument(
        "--real",
        action="store_true",
        help="use the ambient (TPU) devices instead of virtual CPU ones",
    )
    ap.add_argument(
        "--limb-backend", choices=("vpu", "mxu"), default=None
    )
    ap.add_argument(
        "--autotune-from", default=None,
        help="replay a recorded autotune decision JSON in every "
        "sweep child before measuring",
    )
    ap.add_argument(
        "--json-out", default=None, help="write the sweep table here"
    )
    args = ap.parse_args()
    if args.child:
        _child(args)
        return
    counts = [int(c) for c in args.counts.split(",")]
    rows = [_spawn(d, args) for d in counts]
    base = rows[0]["sets_per_sec"] / rows[0]["devices"]
    for r in rows:
        # base is 0.0 in reps=0 smoke mode (no timed rep ran)
        r["efficiency"] = (
            round(r["sets_per_sec"] / (base * r["devices"]), 3)
            if base > 0
            else None
        )
    from lodestar_tpu.utils.provenance import provenance

    out = {
        "workload": f"{args.sets} sets x {args.reps} reps, fixed batch",
        "provenance": provenance(),
        "platform": rows[0]["platform"],
        "limb_backend": rows[0]["limb_backend"],
        "rows": rows,
    }
    print(json.dumps(out, indent=2))
    print("\n| devices | sets/s | efficiency | ok |")
    print("|---|---|---|---|")
    for r in rows:
        print(
            f"| {r['devices']} | {r['sets_per_sec']} | "
            f"{r['efficiency']} | {r['ok']} |"
        )
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()

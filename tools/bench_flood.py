"""Serving-tier flood benchmark: the ISSUE-20 acceptance numbers.

Drives a dev node's REST serving tier (api/overload.py + api/server.py)
through two phases and reports the overload contract as data:

  1. quiet — duty-class requests (produceAttestationData) alone, for
     the baseline p50/p99;
  2. flood — reader threads hammer the light class (70% one hot
     cacheable light-client read, 30% varied per-validator reads that
     miss the cache) while the duty reader keeps going.

The event loop stays QUIET during the flood (no block imports), so
the brownout ladder stays out of the way and the refusals exercised
are the token bucket's 429s and the queue-deadline 503s — the wire
behavior the scenario (lightclient_flood) cannot isolate because its
loop is busy importing. Between them the two tools cover both shed
paths.

The JSON carries the acceptance checks evaluated machine-side:

  - duty p99 under flood within 2x the quiet baseline
  - >= 95% of sheds on the light/admin/conn classes, zero on duty
  - zero 500/501/502 (refusals are typed 429/503)
  - every 429/503 carries Retry-After
  - response-cache hit ratio >= 0.5 on the flood mix

Exit code 1 when any check fails. No TPU involved: the serving tier
is host-side by design, so the committed artifact is honest on CPU
(the provenance stamp says which environment produced it).

  python tools/bench_flood.py --json-out BENCH_flood.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import threading
import time

sys.path.insert(0, ".")


def _http_get(url: str, timeout: float = 10.0):
    """(status, headers, body) — HTTPError is a response, not a crash."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers), body


def _quantile(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    if not ys:
        return 0.0
    i = min(len(ys) - 1, int(q * len(ys)))
    return ys[i]


class _StubVerifier:
    """The bench measures the serving tier; block-import BLS (pure
    python off-device) is stubbed so warm-up costs seconds."""

    async def verify_signature_sets(self, sets, **kw):
        return True

    async def verify_signature_sets_same_message(self, sets, message):
        return [True] * len(sets)

    def can_accept_work(self):
        return True

    async def close(self):
        pass


async def _bench(args) -> dict:
    from lodestar_tpu.api.impl import BeaconApiImpl
    from lodestar_tpu.api.overload import (
        CLS_ADMIN,
        CLS_CONN,
        CLS_DUTY,
        CLS_LIGHT,
        BrownoutLadder,
        ClassBudget,
        LoopLagProbe,
        ServingOverload,
    )
    from lodestar_tpu.api.server import BeaconRestApiServer
    from lodestar_tpu.chain import DevNode
    from lodestar_tpu.config.chain_config import ChainConfig
    from lodestar_tpu.lightclient import LightClientServer
    from lodestar_tpu.types import ssz_types

    FAR = 2**64 - 1
    cfg = ChainConfig(
        ALTAIR_FORK_EPOCH=0,
        BELLATRIX_FORK_EPOCH=FAR,
        CAPELLA_FORK_EPOCH=FAR,
        DENEB_FORK_EPOCH=FAR,
        ELECTRA_FORK_EPOCH=FAR,
        SHARD_COMMITTEE_PERIOD=0,
    )
    types = ssz_types()
    node = DevNode(
        cfg, types, 32, verifier=_StubVerifier(),
        verify_attestations=False,
    )
    node.chain.light_client_server = LightClientServer(
        cfg, types, node.chain
    )
    # tight light budget so the bucket visibly refuses at bench scale;
    # duty wide open — the asymmetry under measurement
    budgets = {
        CLS_DUTY: ClassBudget(10000.0, 4000.0, 32, 5.0),
        CLS_LIGHT: ClassBudget(
            args.light_rate, args.light_burst, 8, 0.05
        ),
    }
    # generous lag thresholds keep the ladder closed on a quiet loop:
    # this bench isolates the bucket/deadline refusal path (the
    # lightclient_flood scenario covers the brownout path)
    ladder = BrownoutLadder(
        thresholds={CLS_ADMIN: 0.5, CLS_LIGHT: 1.0, "consensus": 2.0}
    )
    overload = ServingOverload(
        budgets=budgets, ladder=ladder, pool_workers=24
    )
    overload.cache.attach(node.chain.events)
    probe = LoopLagProbe(ladder, interval=0.05)
    impl = BeaconApiImpl(cfg, types, node.chain)
    server = BeaconRestApiServer(
        impl, port=0, loop=asyncio.get_running_loop(),
        overload=overload,
    )
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    probe.start(asyncio.get_running_loop())
    try:
        await node.run_until(args.warm_slots)

        duty_url = (
            f"{base}/eth/v1/validator/attestation_data"
            f"?slot={node.slot}&committee_index=0"
        )

        # -- phase 1: quiet duty baseline
        quiet: list[float] = []
        for _ in range(args.quiet_requests):
            t0 = time.monotonic()
            status, _h, _b = _http_get(duty_url)
            quiet.append(time.monotonic() - t0)
            assert status == 200, f"quiet duty request got {status}"
        quiet_p99 = _quantile(quiet, 0.99)

        # prime the hot cacheable route while the bucket is full
        hot_url = (
            f"{base}/eth/v1/beacon/light_client/optimistic_update"
        )
        _http_get(hot_url)

        # -- phase 2: flood + concurrent duty reader
        stop = threading.Event()
        statuses: list[tuple[int, bool]] = []
        st_lock = threading.Lock()

        def flood_reader(i: int):
            rng = random.Random(4000 + i)
            for _ in range(args.reqs_per_thread):
                if stop.is_set():
                    break
                if rng.random() < 0.7:
                    url = hot_url
                else:
                    vid = rng.randrange(32)
                    url = (f"{base}/eth/v1/beacon/states/head/"
                           f"validators/{vid}")
                status, headers, _b = _http_get(url)
                with st_lock:
                    statuses.append(
                        (status, "Retry-After" in headers)
                    )
                time.sleep(0.002)

        duty_flood: list[float] = []

        def duty_reader():
            while not stop.is_set():
                t0 = time.monotonic()
                status, _h, _b = _http_get(duty_url)
                duty_flood.append(time.monotonic() - t0)
                with st_lock:
                    statuses.append((status, False))
                time.sleep(0.01)

        readers = [
            threading.Thread(
                target=flood_reader, args=(i,), daemon=True
            )
            for i in range(args.threads)
        ]
        duty_t = threading.Thread(target=duty_reader, daemon=True)
        t_flood = time.monotonic()
        for t in readers:
            t.start()
        duty_t.start()
        while any(t.is_alive() for t in readers):
            await asyncio.sleep(0.1)
        stop.set()
        for t in readers:
            t.join(timeout=10)
        duty_t.join(timeout=10)
        flood_wall = time.monotonic() - t_flood
    finally:
        probe.stop()
        server.stop()
        await node.close()

    # -- the acceptance checks, machine-evaluated -----------------------
    flood_p99 = _quantile(duty_flood, 0.99)
    p99_bound = max(2 * quiet_p99, 0.25)

    sheds = overload.shed_counts()
    total_sheds = sum(sheds.values())
    cheap = {CLS_LIGHT, CLS_ADMIN, CLS_CONN}
    cheap_sheds = sum(
        n for (cls, _r), n in sheds.items() if cls in cheap
    )
    duty_sheds = sum(
        n for (cls, _r), n in sheds.items() if cls == CLS_DUTY
    )

    status_hist: dict[int, int] = {}
    for s, _ra in statuses:
        status_hist[s] = status_hist.get(s, 0) + 1
    client_5xx = sum(
        n for s, n in status_hist.items() if s in (500, 501, 502)
    )
    server_5xx = sum(
        n for s, n in overload.response_counts().items()
        if s in (500, 501, 502)
    )

    refused = [(s, ra) for s, ra in statuses if s in (429, 503)]
    refusals_with_header = sum(1 for _s, ra in refused if ra)

    ratio = overload.cache.hit_ratio()

    checks = {
        "duty_p99_within_2x_quiet": flood_p99 <= p99_bound,
        "sheds_on_cheap_classes_ge_95pct": (
            total_sheds > 0
            and duty_sheds == 0
            and cheap_sheds / total_sheds >= 0.95
        ),
        "zero_500s": client_5xx == 0 and server_5xx == 0,
        "refusals_carry_retry_after": (
            len(refused) > 0
            and refusals_with_header == len(refused)
        ),
        "cache_hit_ratio_ge_floor": ratio >= 0.5,
    }

    from lodestar_tpu.utils.provenance import provenance

    return {
        "metric": "api_serving_read_flood",
        "provenance": provenance(),
        "profile": {
            "warm_slots": args.warm_slots,
            "quiet_requests": args.quiet_requests,
            "flood_threads": args.threads,
            "reqs_per_thread": args.reqs_per_thread,
            "light_budget": {
                "rate": args.light_rate,
                "burst": args.light_burst,
                "max_concurrent": 8,
                "queue_deadline_s": 0.05,
            },
        },
        "quiet": {
            "requests": len(quiet),
            "p50_ms": round(_quantile(quiet, 0.5) * 1e3, 2),
            "p99_ms": round(quiet_p99 * 1e3, 2),
        },
        "flood": {
            "wall_s": round(flood_wall, 3),
            "requests": len(statuses),
            "requests_per_sec": round(
                len(statuses) / flood_wall, 1
            ),
            "duty_requests": len(duty_flood),
            "duty_p50_ms": round(
                _quantile(duty_flood, 0.5) * 1e3, 2
            ),
            "duty_p99_ms": round(flood_p99 * 1e3, 2),
            "duty_p99_bound_ms": round(p99_bound * 1e3, 2),
        },
        "statuses": {
            str(s): n for s, n in sorted(status_hist.items())
        },
        "sheds": {
            f"{cls}/{reason}": n
            for (cls, reason), n in sorted(sheds.items())
        },
        "shed_summary": {
            "total": total_sheds,
            "duty": duty_sheds,
            "cheap_share": round(
                cheap_sheds / total_sheds, 4
            ) if total_sheds else 0.0,
        },
        "retry_after": {
            "refusals": len(refused),
            "with_header": refusals_with_header,
        },
        "cache": {
            **overload.cache.counts(),
            "hit_ratio": round(ratio, 4),
        },
        "brownout_samples": ladder.samples,
        "checks": checks,
        "passed": all(checks.values()),
        "caveat": (
            "serving tier is host-side by design: CPU numbers are "
            "the real thing for admission/cache behavior; absolute "
            "latency shares one machine between flood clients and "
            "the node (the real adversary is remote)"
        ),
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--warm-slots", type=int, default=4,
                   help="dev-chain slots before measuring (altair "
                   "from genesis; the optimistic update exists after "
                   "the first imported sync aggregate)")
    p.add_argument("--quiet-requests", type=int, default=100,
                   help="duty requests in the quiet baseline phase")
    p.add_argument("--threads", type=int, default=6,
                   help="flood reader threads")
    p.add_argument("--reqs-per-thread", type=int, default=300,
                   help="requests each flood reader issues")
    p.add_argument("--light-rate", type=float, default=150.0,
                   help="light-class token rate (req/s)")
    p.add_argument("--light-burst", type=float, default=30.0,
                   help="light-class bucket depth")
    p.add_argument("--json-out", default=None)
    args = p.parse_args()
    out = asyncio.run(_bench(args))
    line = json.dumps(out, indent=2)
    print(line)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    if not out["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

"""Characterize axon-tunnel dispatch costs: same-buffer replay vs
evolving device buffers vs fresh uploads vs async pipelining."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from lodestar_tpu.utils import jaxcache  # noqa: E402

jaxcache.enable()


@jax.jit
def f(x):
    # nontrivial: a few fused ops, keeps shape
    return (x * 3 + 1) ^ (x >> 2)


def main() -> None:
    print(f"platform={jax.default_backend()}", flush=True)
    x0 = jnp.asarray(np.arange(2048, dtype=np.int32))
    jax.block_until_ready(f(x0))

    # A: same buffer repeated
    t0 = time.perf_counter()
    for _ in range(20):
        jax.block_until_ready(f(x0))
    print(f"A same-buffer blocking: {(time.perf_counter() - t0) / 20 * 1e3:.1f} ms/call", flush=True)

    # B: evolving device buffer
    x = x0
    t0 = time.perf_counter()
    for _ in range(20):
        x = f(x)
        jax.block_until_ready(x)
    print(f"B evolving blocking: {(time.perf_counter() - t0) / 20 * 1e3:.1f} ms/call", flush=True)

    # B2: evolving, block only at end
    x = x0
    t0 = time.perf_counter()
    for _ in range(20):
        x = f(x)
    jax.block_until_ready(x)
    print(f"B2 evolving async: {(time.perf_counter() - t0) / 20 * 1e3:.1f} ms/call", flush=True)

    # C: fresh upload each call (blocking)
    t0 = time.perf_counter()
    for i in range(10):
        xi = jnp.asarray(np.arange(2048, dtype=np.int32) + i)
        jax.block_until_ready(f(xi))
    print(f"C fresh-upload blocking: {(time.perf_counter() - t0) / 10 * 1e3:.1f} ms/call", flush=True)

    # D: fresh uploads, block only at end (pipelined)
    t0 = time.perf_counter()
    outs = []
    for i in range(10):
        xi = jnp.asarray(np.arange(2048, dtype=np.int32) + i)
        outs.append(f(xi))
    jax.block_until_ready(outs)
    print(f"D fresh-upload async: {(time.perf_counter() - t0) / 10 * 1e3:.1f} ms/call", flush=True)

    # E: upload-only cost
    t0 = time.perf_counter()
    for i in range(10):
        jax.block_until_ready(jax.device_put(np.arange(2048, dtype=np.int32) + i))
    print(f"E device_put blocking: {(time.perf_counter() - t0) / 10 * 1e3:.1f} ms/call", flush=True)

    # F: download-only cost (scalar readback)
    s = f(x0)
    t0 = time.perf_counter()
    for _ in range(10):
        int(np.asarray(s[0]))
    print(f"F scalar readback: {(time.perf_counter() - t0) / 10 * 1e3:.1f} ms/call", flush=True)


if __name__ == "__main__":
    main()

"""Micro-probe: true device cost of verify-pipeline pieces at batch
2048 on the tunneled chip.

Timing method: every probe jits a wrapper that reduces the result to
ONE scalar, so a timed call costs dispatch + device + exactly one
readback. The measured null-call baseline (~150 ms through the tunnel)
is printed and should be subtracted mentally; per-leaf device_get
timing (the old approach) charged ~150 ms PER ARRAY and made 40 ms
stages look like seconds."""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from lodestar_tpu.crypto.bls.fields import P  # noqa: E402
from lodestar_tpu.ops import curve as C  # noqa: E402
from lodestar_tpu.ops import fq, ingest, tower  # noqa: E402
from lodestar_tpu.ops import limbs as L  # noqa: E402
from lodestar_tpu.utils import jaxcache  # noqa: E402

jaxcache.enable()
N = 2048
rng = np.random.default_rng(5)


def rand_fq(n=N):
    return L.from_ints([int(rng.integers(0, 2**63)) ** 5 % P for _ in range(n)])


def _scalarize(out):
    leaves = jax.tree.leaves(out)
    acc = jnp.int32(0)
    for leaf in leaves:
        if leaf.dtype == jnp.bool_:
            acc = acc + jnp.sum(leaf.astype(jnp.int32))
        else:
            acc = acc + jnp.sum(leaf, dtype=jnp.int32)
    return acc


def t(label, fn, *args, reps=3):
    wrapped = jax.jit(lambda *a: _scalarize(fn(*a)))
    np.asarray(jax.device_get(wrapped(*args)))  # warm/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        np.asarray(jax.device_get(wrapped(*args)))
    print(
        f"{label}: {(time.perf_counter() - t0) / reps * 1000:.1f} ms",
        flush=True,
    )


def main():
    print(f"platform={jax.default_backend()}", flush=True)
    a = (rand_fq(), rand_fq())
    b = (rand_fq(), rand_fq())

    def mul32(x, y):
        for _ in range(32):
            x = fq.mul(x, y)
        return x

    def eq8(x, y):
        return [fq.eq(fq.mul(x, y), x) for _ in range(8)]

    t("null baseline", lambda x: x, a[0], reps=5)
    t("fq.mul x1", lambda x, y: fq.mul(x, y), a[0], b[0])
    t("fq.mul x32", mul32, a[0], b[0])
    t("fq.eq x8", eq8, a[0], b[0])
    t("fq.inv chain", lambda x: fq.inv(x), a[0])
    t("fq2_sqrt_flagged", lambda x: ingest.fq2_sqrt_flagged(x), a)

    x, y, _ = ingest.g2_sqrt_with_sign(a, jnp.zeros(N, bool))
    q = C.jac_from_affine(C.FQ2_OPS, tower.fq2_norm(x), tower.fq2_norm(y))

    t("jac_psi", lambda p: ingest.jac_psi(p), q)
    t("jac_eq", lambda p: ingest.jac_eq(p, p), q)
    t("jac_add", lambda p: C.jac_add(C.FQ2_OPS, p, p), q)

    from lodestar_tpu.ops import pallas_ladder as PL

    bits = jnp.broadcast_to(jnp.asarray(ingest._x_bits()), (N, 64))
    t(
        "pallas ladder [x]Q",
        lambda qx0, qx1, qy0, qy1, b_: PL.g2_scalar_mul(
            (qx0, qx1), (qy0, qy1), b_
        ),
        q.x[0], q.x[1], q.y[0], q.y[1], bits,
    )
    t("scan ladder [x]Q", lambda p: ingest._mul_x_abs(p, (N,)), q)
    t("g2_in_subgroup", lambda p: ingest.g2_in_subgroup(p, (N,)), q)
    t("g2_clear_cofactor", lambda p: ingest.g2_clear_cofactor(p, (N,)), q)
    t("sswu single", lambda u: ingest._sswu(u), a)
    t("iso_map", lambda u: ingest._iso_map(u, u), a)

    # prepare-stage pieces
    from lodestar_tpu.bls import kernels
    from lodestar_tpu.bls.verifier import _rand_scalars

    rbits = C.scalars_to_bits(_rand_scalars(N), kernels.RAND_BITS)
    g1x, g1y = rand_fq(), rand_fq()
    t(
        "G1 scan ladder (rand)",
        lambda px, py, b_: C.scalar_mul(C.FQ_OPS, px, py, b_),
        g1x, g1y, rbits,
    )
    t(
        "G2 jac_sum_scan",
        lambda p: C.jac_sum_scan(C.FQ2_OPS, p),
        q,
    )
    # product stage at the pairing batch shape
    f12 = tuple(
        tuple((rand_fq(N + 1), rand_fq(N + 1)) for _ in range(3))
        for _ in range(2)
    )
    mask = jnp.ones(N + 1, bool)
    from lodestar_tpu.ops import pairing

    t(
        "fq12 masked product (2049)",
        lambda m: pairing._fq12_masked_product(f12, m),
        mask,
    )


if __name__ == "__main__":
    main()

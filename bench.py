"""Headline benchmark: BLS signature-set batch verification throughput.

Measures the device pipeline behind `IBlsVerifier.verify_signature_sets`
(BASELINE.json config #2: batch-verify 128 attestation SignatureSets) —
random-weighted scalar ladders, masked aggregation, batched Miller loop,
one shared final exponentiation — end-to-end on the default JAX platform
(the real TPU under the driver; CPU elsewhere).

Baseline: the reference verifies ~100 signature sets in ~45 ms on its CPU
blst worker pool (chain/blocks/verifyBlocksSignatures.ts:45; BASELINE.md)
= ~2,222 sets/sec. vs_baseline = our sets/sec / 2222.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

# Device bucket: the verifier packs <=128-set jobs into one big device
# batch (the analog of prepareWork's 128-set packing, scaled to what
# one chip absorbs: per-op device cost is batch-flat up to ~2048, so
# large buckets are nearly free throughput).
N_SETS = 2048
ITERS = 8
BASELINE_SETS_PER_SEC = 100 / 0.045  # reference: ~100 sigs / 45 ms


def main() -> None:
    import jax
    import jax.numpy as jnp

    from lodestar_tpu.bls import kernels
    from lodestar_tpu.bls.verifier import _rand_scalars
    from lodestar_tpu.crypto.bls import curve as oc
    from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2
    from lodestar_tpu.ops import curve as C
    from lodestar_tpu.params import BLS_DST_SIG

    print(f"# platform: {jax.default_backend()}, devices: {len(jax.devices())}",
          file=sys.stderr)

    # Build valid (pk, H(msg), sig) sets with the (native-backed)
    # oracle; distinct keys/messages per set.
    pks, hs, sigs = [], [], []
    for i in range(N_SETS):
        sk = 10_000 + i
        msg = i.to_bytes(32, "little")
        h = hash_to_g2(msg, BLS_DST_SIG)
        pks.append(oc.g1_mul(oc.G1_GEN, sk))
        hs.append(h)
        sigs.append(oc.g2_mul(h, sk))

    pk_dev = C.g1_batch_from_ints(pks)
    h_dev = C.g2_batch_from_ints(hs)
    sig_dev = C.g2_batch_from_ints(sigs)
    mask = jnp.ones(N_SETS, dtype=bool)

    all_true = jax.jit(lambda xs: jnp.stack(xs).all())

    def submit():
        bits = C.scalars_to_bits(_rand_scalars(N_SETS), kernels.RAND_BITS)
        return kernels.run_verify_batch_async(
            pk_dev, (h_dev.x, h_dev.y), sig_dev, bits, mask
        )

    # Warmup: compile the pipeline + reduce, and verify correctness
    # with a blocking call.
    ok = kernels.run_verify_batch(
        pk_dev,
        (h_dev.x, h_dev.y),
        sig_dev,
        C.scalars_to_bits(_rand_scalars(N_SETS), kernels.RAND_BITS),
        mask,
    )
    if not ok:
        raise RuntimeError("batch verify returned False on valid sets")
    bool(all_true([submit(), submit()]))

    # Measured run: ITERS verifies submitted asynchronously, verdicts
    # reduced on device, ONE readback — the production shape: the
    # verifier service batches verdict readbacks inside the reference's
    # own 100 ms gossip window (a fresh-result readback through the
    # tunnel costs ~100 ms; dispatches are ~0.1 ms).
    t0 = time.perf_counter()
    oks = [submit() for _ in range(ITERS)]
    if not bool(all_true(oks)):
        raise RuntimeError("batch verify returned False on valid sets")
    dt = time.perf_counter() - t0

    sets_per_sec = N_SETS * ITERS / dt
    print(json.dumps({
        "metric": "bls_batch_verify_sets_per_sec",
        "value": round(sets_per_sec, 2),
        "unit": f"sets/sec (random-lincomb batch verify, {N_SETS}-set device bucket)",
        "vs_baseline": round(sets_per_sec / BASELINE_SETS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()

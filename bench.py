"""Headline benchmark: BLS batch verification through the PRODUCTION path.

Drives `TpuBlsVerifier.verify_signature_sets` (the IBlsVerifier seam,
chain/bls/multithread/index.ts:113) exactly the way block import does:
concurrent jobs of <=128 compressed signature sets (BASELINE.json
config #3 shape), verified end-to-end — host decompression + hash-to-G2
on the prep thread pool, wave packing into 2048-set device buckets,
async dispatch, one verdict readback per wave, mesh-sharded when more
than one device is visible. Unlike rounds 1-2 this measures the same
code path production runs (VERDICT r2 weak #2).

Baseline: the reference verifies ~100 signature sets in ~45 ms on its
CPU blst worker pool (chain/blocks/verifyBlocksSignatures.ts:45;
BASELINE.md) = ~2,222 sets/sec. vs_baseline = our sets/sec / 2222.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import asyncio
import json
import sys
import time

N_JOBS = 16  # concurrent verify jobs per wave (block-import shaped)
SETS_PER_JOB = 128  # reference MAX_SIGNATURE_SETS_PER_JOB
WAVES = 4  # measured waves (+1 warmup)
KEY_POOL = 2048  # distinct validator keys (pubkey cache is production-warm)
BASELINE_SETS_PER_SEC = 100 / 0.045  # reference: ~100 sigs / 45 ms


def _build_sets(n: int, tag: int):
    """n valid compressed SignatureSets with distinct messages. Small
    secret scalars keep setup time sane; verification cost does not
    depend on the scalar. Pure benchmark fixture construction — NOT
    part of the measured path."""
    from lodestar_tpu.bls import SignatureSet
    from lodestar_tpu.crypto.bls import native
    from lodestar_tpu.crypto.bls import curve as oc
    from lodestar_tpu.params import BLS_DST_SIG

    dst = bytes(BLS_DST_SIG)
    out = []
    for i in range(n):
        sk = 3 + (tag * n + i) % KEY_POOL
        msg = (tag * n + i).to_bytes(32, "little")
        h = native.hash_to_g2(msg, dst)
        pk = oc.g1_to_bytes(native.g1_mul(oc.G1_GEN, sk))
        sig = oc.g2_to_bytes(native.g2_mul(h, sk))
        out.append(SignatureSet(pk, msg, sig))
    return out


def _build_all_waves():
    """Fixture sets for warmup + measured waves, built in parallel on a
    thread pool (the native C calls release the GIL)."""
    from concurrent.futures import ThreadPoolExecutor

    tags = range((1 + WAVES) * N_JOBS)
    with ThreadPoolExecutor(8) as pool:
        jobs = list(
            pool.map(lambda t: _build_sets(SETS_PER_JOB, t), tags)
        )
    return [
        jobs[w * N_JOBS : (w + 1) * N_JOBS] for w in range(1 + WAVES)
    ]


_AUTOTUNE_DECISION = None  # loaded by --autotune-from (main)


async def _run(depth: int | None = None, waves=None) -> float:
    from lodestar_tpu.bls import TpuBlsVerifier

    if waves is None:
        waves = _build_all_waves()
    v = (
        TpuBlsVerifier(pipeline_depth=depth)
        if depth
        else TpuBlsVerifier()
    )
    if _AUTOTUNE_DECISION is not None:
        # the kernel-side knobs were replayed in main() (where an
        # explicit --limb-backend then wins); here apply only the
        # verifier-side knobs — re-running the FULL decision would
        # silently switch the backend back and defeat the A/B flag
        v.set_latency_budget_ms(
            float(_AUTOTUNE_DECISION["config"]["latency_budget_ms"])
        )
        tuned_depth = int(
            _AUTOTUNE_DECISION["config"].get("pipeline_depth", 0)
        )
        if depth is None and tuned_depth:
            # explicit --pipeline-depth wins over the replay (A/B
            # sweeps against the tuned config), like --limb-backend
            v.set_pipeline_depth(tuned_depth)

    async def run_wave(jobs) -> bool:
        results = await asyncio.gather(
            *(v.verify_signature_sets(job) for job in jobs)
        )
        return all(results)

    # Warmup: compiles the 2048-set bucket pipeline (persistent-cached)
    # and checks correctness through the full production path.
    if not await run_wave(waves[0]):
        raise RuntimeError("verifier returned False on valid sets")

    t0 = time.perf_counter()
    # All waves' jobs enqueued concurrently: the verifier drains the
    # queue into 2048-set buckets and pipelines host prep of wave k+1
    # under device execution of wave k.
    oks = await asyncio.gather(*(run_wave(w) for w in waves[1:]))
    dt = time.perf_counter() - t0
    await v.close()
    if not all(oks):
        raise RuntimeError("verifier returned False on valid sets")
    return N_JOBS * SETS_PER_JOB * WAVES / dt


async def _sweep(depths: list[int]) -> dict[int, float]:
    """A/B the overlapped pipeline: the SAME fixture waves measured
    once per requested depth (depth 1 = synchronous dispatch), each
    on a fresh verifier so queue state never leaks across points.
    One throwaway pass runs first: the measured phase packs buckets
    the per-run warmup wave cannot predict, and whichever depth runs
    first would otherwise absorb those shapes' compile/cache loads
    inside its timed window (measured: a 10x phantom 'speedup')."""
    waves = _build_all_waves()
    await _run(depths[0], waves)
    return {d: await _run(d, waves) for d in depths}


def _actual_limb_backend() -> str:
    """Report the backend that actually ran — the env var alone (no
    --limb-backend flag) also selects it at limbs import time."""
    from lodestar_tpu.ops import limbs as _L

    return _L.get_backend()


def main() -> None:
    # --mesh N: multi-chip mode (BASELINE config #5). With >= N real
    # devices a Mesh shards each bucket's batch axis over them; with
    # fewer (this host has one tunneled chip) the flag falls back to N
    # VIRTUAL CPU devices so the sharded path is exercised end-to-end —
    # absolute CPU numbers are meaningless, but the scaling curve and
    # the sharding correctness are real. Env must be set before jax
    # imports, so we re-exec.
    import os

    # --limb-backend {vpu,mxu}: select the Fq limb arithmetic backend
    # (ops/limbs.py LimbBackend) BEFORE anything traces, so every jitted
    # stage and Pallas kernel builds for the requested unit. Exported as
    # the env var so mesh-mode re-exec children inherit it.
    limb_backend = None
    if "--limb-backend" in sys.argv:
        limb_backend = sys.argv[sys.argv.index("--limb-backend") + 1]
        os.environ["LODESTAR_TPU_LIMB_BACKEND"] = limb_backend
        from lodestar_tpu.ops import limbs as _L

        _L.set_backend(limb_backend)

    # --autotune-from AUTOTUNE.json: replay a recorded autotune
    # decision (device/autotune.py) — the bench then measures the
    # exact configuration the tuner picked on this host, and the
    # provenance stamp records the replay. Applied before anything
    # traces; exported via the env var so mesh-mode re-exec children
    # inherit the backend. An EXPLICIT --limb-backend wins over the
    # replayed backend (A/B runs against the tuned config), matching
    # the precedence the sibling benches document.
    global _AUTOTUNE_DECISION
    if "--autotune-from" in sys.argv:
        from lodestar_tpu.device import autotune as _at

        path = sys.argv[sys.argv.index("--autotune-from") + 1]
        _AUTOTUNE_DECISION = _at.load_decision(path)
        cfg = _at.apply_decision(_AUTOTUNE_DECISION)
        os.environ["LODESTAR_TPU_INGEST_MIN_BUCKET"] = str(
            cfg.ingest_min_bucket
        )
        if limb_backend is not None:
            from lodestar_tpu.ops import limbs as _L

            if _L.get_backend() != limb_backend:
                _L.set_backend(limb_backend)
        else:
            os.environ["LODESTAR_TPU_LIMB_BACKEND"] = cfg.limb_backend

    # --pipeline-depth N | N,M,...: sweep the verifier's wave-overlap
    # depth (bls/verifier.py double buffering). A single N > 1 implies
    # the sync baseline too (A/B: {1, N}); a comma list runs exactly
    # those depths. Headline value = the deepest point; the sweep and
    # the overlap speedup land in the "pipeline" JSON object.
    depths: list[int] | None = None
    if "--pipeline-depth" in sys.argv:
        raw = sys.argv[sys.argv.index("--pipeline-depth") + 1]
        depths = sorted(
            {max(1, int(x)) for x in raw.split(",") if x.strip()}
        )
        if depths == []:
            raise SystemExit("--pipeline-depth: empty depth list")
        if len(depths) == 1 and depths[0] > 1:
            depths = [1] + depths

    mesh_n = 0
    if "--mesh" in sys.argv:
        mesh_n = int(sys.argv[sys.argv.index("--mesh") + 1])
    if mesh_n and os.environ.get("_BENCH_MESH") != str(mesh_n):
        import subprocess

        import jax

        if len(jax.devices()) < mesh_n:
            env = dict(
                os.environ,
                _BENCH_MESH=str(mesh_n),
                JAX_PLATFORMS="cpu",
                XLA_FLAGS=(
                    os.environ.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={mesh_n}"
                ).strip(),
            )
            raise SystemExit(
                subprocess.call([sys.executable] + sys.argv, env=env)
            )
        os.environ["_BENCH_MESH"] = str(mesh_n)
    if mesh_n and os.environ.get("_BENCH_MESH") == str(mesh_n):
        # re-exec'd child: the ambient sitecustomize may import jax
        # before the env var is read — force via the config API too
        # (same dance as tests/conftest.py)
        import jax as _jax

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            try:
                _jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
    import jax

    print(
        f"# platform: {jax.default_backend()}, devices: {len(jax.devices())}"
        + (f", mesh: {mesh_n}" if mesh_n else ""),
        file=sys.stderr,
    )
    if jax.default_backend() == "cpu":
        # CPU fallback (virtual-device mesh runs AND containers with
        # no chip): shrink the workload — the XLA-scan CPU path is
        # ~100x the chip, so these runs validate sharding / pipeline
        # mechanics, not absolute throughput
        global N_JOBS, SETS_PER_JOB, WAVES
        N_JOBS, SETS_PER_JOB, WAVES = 4, 16, 2
    from lodestar_tpu.utils.provenance import provenance

    pipeline = None
    if depths:
        results = asyncio.run(_sweep(depths))
        sets_per_sec = results[max(depths)]
        pipeline = {
            "depths": {str(d): round(s, 2) for d, s in results.items()}
        }
        if results.get(1):
            pipeline["overlap_speedup"] = round(
                results[max(depths)] / results[1], 4
            )
        if jax.default_backend() == "cpu":
            pipeline["caveat"] = (
                "NO TPU in this container: host prep and the XLA-"
                "emulated 'device' waves share ONE CPU core, so "
                "overlap hides nothing and depth>1 measures only "
                "the pipeline's bookkeeping overhead. The depth "
                "sweep exists to prove bit-identical verdicts and "
                "exercise the double-buffered dispatch end to end; "
                "run the REAL_CAMPAIGN pipeline step on TPU "
                "hardware for the chip speedup."
            )
    else:
        sets_per_sec = asyncio.run(_run())
    payload = {
        "metric": "bls_verify_sets_per_sec_production",
        "value": round(sets_per_sec, 2),
        "unit": (
            "sets/sec (TpuBlsVerifier.verify_signature_sets, "
            f"{N_JOBS}x{SETS_PER_JOB}-set jobs/wave, compressed in)"
        ),
        "limb_backend": _actual_limb_backend(),
        "vs_baseline": round(sets_per_sec / BASELINE_SETS_PER_SEC, 4),
        "provenance": provenance(),
    }
    if pipeline is not None:
        payload["pipeline"] = pipeline
    print(json.dumps(payload))


if __name__ == "__main__":
    main()

/* BLS12-381 native backend: field towers, curves, pairing, hash-to-G2.
 *
 * Reference analog: the supranational blst library behind
 * @chainsafe/blst (SURVEY.md §2.1 row 1) — the reference's only crypto
 * engine. Here the TPU kernels (lodestar_tpu/ops) are the batch engine
 * and this library is the serial host side: decompression + subgroup
 * checks + hash-to-curve in front of device dispatch (the
 * aggregateWithRandomness-class host bottleneck, VERDICT r1 #10), and
 * a fast oracle for tests. Math follows this repo's own pure-Python
 * oracle (lodestar_tpu/crypto/bls/*, KAT-validated); constants are
 * generated from it by tools/gen_bls_constants.py.
 *
 * Representation: Fp = 6x64-bit limbs, little-endian, Montgomery form
 * (R = 2^384). Points are Jacobian internally; the ABI uses affine
 * big-endian byte strings (48B per Fp), all-zero = infinity.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include "bls381_constants.h"

typedef unsigned __int128 u128;

/* ------------------------------------------------------------------ */
/* Fp arithmetic (Montgomery)                                          */
/* ------------------------------------------------------------------ */

static inline int fp_is_zero(const fp_t *a) {
  uint64_t r = 0;
  for (int i = 0; i < 6; i++) r |= a->l[i];
  return r == 0;
}

static inline int fp_eq(const fp_t *a, const fp_t *b) {
  uint64_t r = 0;
  for (int i = 0; i < 6; i++) r |= a->l[i] ^ b->l[i];
  return r == 0;
}

static inline int fp_gte_p(const fp_t *a) {
  for (int i = 5; i >= 0; i--) {
    if (a->l[i] > FP_P.l[i]) return 1;
    if (a->l[i] < FP_P.l[i]) return 0;
  }
  return 1; /* equal */
}

static inline void fp_sub_p(fp_t *a) {
  uint64_t borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a->l[i] - FP_P.l[i] - borrow;
    a->l[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

static void fp_add(fp_t *out, const fp_t *a, const fp_t *b) {
  uint64_t carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)a->l[i] + b->l[i] + carry;
    out->l[i] = (uint64_t)s;
    carry = (uint64_t)(s >> 64);
  }
  if (carry || fp_gte_p(out)) fp_sub_p(out);
}

static void fp_sub(fp_t *out, const fp_t *a, const fp_t *b) {
  uint64_t borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a->l[i] - b->l[i] - borrow;
    out->l[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {
    uint64_t carry = 0;
    for (int i = 0; i < 6; i++) {
      u128 s = (u128)out->l[i] + FP_P.l[i] + carry;
      out->l[i] = (uint64_t)s;
      carry = (uint64_t)(s >> 64);
    }
  }
}

static void fp_neg(fp_t *out, const fp_t *a) {
  if (fp_is_zero(a)) {
    *out = *a;
    return;
  }
  fp_sub(out, &FP_P, a);
  /* FP_P - a where a < p is already canonical */
}

static void fp_dbl(fp_t *out, const fp_t *a) { fp_add(out, a, a); }

/* CIOS Montgomery multiplication */
static void fp_mul(fp_t *out, const fp_t *a, const fp_t *b) {
  uint64_t t[8] = {0};
  for (int i = 0; i < 6; i++) {
    uint64_t carry = 0;
    for (int j = 0; j < 6; j++) {
      u128 s = (u128)a->l[j] * b->l[i] + t[j] + carry;
      t[j] = (uint64_t)s;
      carry = (uint64_t)(s >> 64);
    }
    u128 s = (u128)t[6] + carry;
    t[6] = (uint64_t)s;
    t[7] = (uint64_t)(s >> 64);

    uint64_t m = t[0] * FP_INV;
    u128 c = (u128)m * FP_P.l[0] + t[0];
    carry = (uint64_t)(c >> 64);
    for (int j = 1; j < 6; j++) {
      c = (u128)m * FP_P.l[j] + t[j] + carry;
      t[j - 1] = (uint64_t)c;
      carry = (uint64_t)(c >> 64);
    }
    c = (u128)t[6] + carry;
    t[5] = (uint64_t)c;
    t[6] = t[7] + (uint64_t)(c >> 64);
    t[7] = 0;
  }
  fp_t r;
  for (int i = 0; i < 6; i++) r.l[i] = t[i];
  if (t[6] || fp_gte_p(&r)) fp_sub_p(&r);
  *out = r;
}

static void fp_sqr(fp_t *out, const fp_t *a) { fp_mul(out, a, a); }

/* exponentiation by a plain (non-Montgomery) little-endian exponent;
   MSB-first square-and-multiply (1^2 = 1, so leading squares are free) */
static void fp_pow(fp_t *out, const fp_t *a, const uint64_t *e, int nlimbs) {
  fp_t acc = FP_ONE_M, base = *a;
  int top = nlimbs * 64 - 1;
  while (top >= 0 && !((e[top / 64] >> (top % 64)) & 1)) top--;
  for (int i = top; i >= 0; i--) {
    fp_sqr(&acc, &acc);
    if ((e[i / 64] >> (i % 64)) & 1) fp_mul(&acc, &acc, &base);
  }
  *out = acc;
}

static void fp_inv(fp_t *out, const fp_t *a) {
  fp_pow(out, a, EXP_P_MINUS_2.l, 6);
}

/* returns 1 and writes sqrt if a is a QR, else 0 */
static int fp_sqrt(fp_t *out, const fp_t *a) {
  fp_t c, c2;
  fp_pow(&c, a, EXP_SQRT.l, 6);
  fp_sqr(&c2, &c);
  if (!fp_eq(&c2, a)) return 0;
  *out = c;
  return 1;
}

static void fp_from_mont(fp_t *out, const fp_t *a) {
  fp_t one = {{1, 0, 0, 0, 0, 0}};
  fp_mul(out, a, &one);
}

static void fp_to_mont(fp_t *out, const fp_t *a) {
  fp_mul(out, a, &FP_R2);
}

static int fp_sgn0(const fp_t *a) { /* canonical LSB */
  fp_t plain;
  fp_from_mont(&plain, a);
  return (int)(plain.l[0] & 1);
}

/* big-endian 48-byte decode (plain) -> Montgomery; returns 0 if >= p */
static int fp_from_bytes(fp_t *out, const uint8_t in[48]) {
  fp_t plain;
  for (int i = 0; i < 6; i++) {
    uint64_t v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | in[(5 - i) * 8 + j];
    plain.l[i] = v;
  }
  if (fp_gte_p(&plain)) return 0;
  fp_to_mont(out, &plain);
  return 1;
}

static void fp_to_bytes(uint8_t out[48], const fp_t *a) {
  fp_t plain;
  fp_from_mont(&plain, a);
  for (int i = 0; i < 6; i++) {
    uint64_t v = plain.l[i];
    for (int j = 0; j < 8; j++) {
      out[(5 - i) * 8 + 7 - j] = (uint8_t)(v & 0xff);
      v >>= 8;
    }
  }
}

/* 64-byte big-endian wide reduction (hash_to_field): hi*2^384 + lo */
static void fp_from_bytes_wide(fp_t *out, const uint8_t in[64]) {
  uint8_t hi_b[48] = {0}, lo_b[48];
  memcpy(hi_b + 32, in, 16); /* top 16 bytes, right-aligned BE */
  memcpy(lo_b, in + 16, 48);
  fp_t hi, lo;
  /* decode plain without range check (reduce via Montgomery muls) */
  for (int k = 0; k < 2; k++) {
    const uint8_t *src = k ? lo_b : hi_b;
    fp_t plain;
    for (int i = 0; i < 6; i++) {
      uint64_t v = 0;
      for (int j = 0; j < 8; j++) v = (v << 8) | src[(5 - i) * 8 + j];
      plain.l[i] = v;
    }
    /* plain may exceed p; Montgomery mul reduces mod p regardless */
    fp_t m;
    fp_mul(&m, &plain, &FP_R2); /* = plain * R mod p */
    if (k)
      lo = m;
    else
      hi = m;
  }
  /* value*R = hi*R*2^384 + lo*R = mont_mul(hi_m, R2)*... :
     hi_m = hi*R; hi*2^384*R = hi*R * (2^384 mod p) * R * R^-1
     = mont_mul(hi_m, to_mont(2^384 mod p)); and to_mont(2^384) = R2 */
  fp_t hi_shift;
  fp_mul(&hi_shift, &hi, &FP_R2);
  fp_add(out, &hi_shift, &lo);
}

/* ------------------------------------------------------------------ */
/* Fp2 = Fp[u]/(u^2+1)                                                 */
/* ------------------------------------------------------------------ */

static const fp2_t FP2_ZERO = {{{0}}, {{0}}};

static void fp2_add(fp2_t *o, const fp2_t *a, const fp2_t *b) {
  fp_add(&o->c0, &a->c0, &b->c0);
  fp_add(&o->c1, &a->c1, &b->c1);
}

static void fp2_sub(fp2_t *o, const fp2_t *a, const fp2_t *b) {
  fp_sub(&o->c0, &a->c0, &b->c0);
  fp_sub(&o->c1, &a->c1, &b->c1);
}

static void fp2_neg(fp2_t *o, const fp2_t *a) {
  fp_neg(&o->c0, &a->c0);
  fp_neg(&o->c1, &a->c1);
}

static void fp2_conj(fp2_t *o, const fp2_t *a) {
  o->c0 = a->c0;
  fp_neg(&o->c1, &a->c1);
}

static void fp2_dbl(fp2_t *o, const fp2_t *a) { fp2_add(o, a, a); }

static void fp2_mul(fp2_t *o, const fp2_t *a, const fp2_t *b) {
  fp_t t0, t1, s0, s1, r0;
  fp_mul(&t0, &a->c0, &b->c0);
  fp_mul(&t1, &a->c1, &b->c1);
  fp_add(&s0, &a->c0, &a->c1);
  fp_add(&s1, &b->c0, &b->c1);
  fp_sub(&r0, &t0, &t1); /* c0 = a0b0 - a1b1 */
  fp_mul(&s0, &s0, &s1);
  fp_sub(&s0, &s0, &t0);
  fp_sub(&s0, &s0, &t1); /* c1 = (a0+a1)(b0+b1) - t0 - t1 */
  o->c0 = r0;
  o->c1 = s0;
}

static void fp2_sqr(fp2_t *o, const fp2_t *a) {
  fp_t s, d, m;
  fp_add(&s, &a->c0, &a->c1);
  fp_sub(&d, &a->c0, &a->c1);
  fp_mul(&m, &a->c0, &a->c1);
  fp_mul(&s, &s, &d); /* c0 = (a0+a1)(a0-a1) */
  o->c0 = s;
  fp_dbl(&o->c1, &m);
}

static void fp2_mul_fp(fp2_t *o, const fp2_t *a, const fp_t *k) {
  fp_mul(&o->c0, &a->c0, k);
  fp_mul(&o->c1, &a->c1, k);
}

/* (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1)u */
static void fp2_mul_by_xi(fp2_t *o, const fp2_t *a) {
  fp_t t0, t1;
  fp_sub(&t0, &a->c0, &a->c1);
  fp_add(&t1, &a->c0, &a->c1);
  o->c0 = t0;
  o->c1 = t1;
}

static void fp2_inv(fp2_t *o, const fp2_t *a) {
  fp_t n, t;
  fp_sqr(&n, &a->c0);
  fp_sqr(&t, &a->c1);
  fp_add(&n, &n, &t); /* norm = a0^2 + a1^2 */
  fp_inv(&n, &n);
  fp_mul(&o->c0, &a->c0, &n);
  fp_neg(&t, &a->c1);
  fp_mul(&o->c1, &t, &n);
}

static int fp2_is_zero(const fp2_t *a) {
  return fp_is_zero(&a->c0) && fp_is_zero(&a->c1);
}

static int fp2_eq(const fp2_t *a, const fp2_t *b) {
  return fp_eq(&a->c0, &b->c0) && fp_eq(&a->c1, &b->c1);
}

static int fp2_sgn0(const fp2_t *a) { /* RFC 9380 sgn0 for m=2 */
  fp_t p0;
  fp_from_mont(&p0, &a->c0);
  int sign0 = (int)(p0.l[0] & 1);
  int zero0 = fp_is_zero(&a->c0);
  fp_t p1;
  fp_from_mont(&p1, &a->c1);
  int sign1 = (int)(p1.l[0] & 1);
  return sign0 | (zero0 & sign1);
}

/* complex sqrt: returns 1 + writes root on success */
static int fp2_sqrt(fp2_t *o, const fp2_t *a) {
  if (fp_is_zero(&a->c1)) {
    fp_t r;
    if (fp_sqrt(&r, &a->c0)) {
      o->c0 = r;
      memset(&o->c1, 0, sizeof(fp_t));
      return 1;
    }
    fp_t na;
    fp_neg(&na, &a->c0);
    if (fp_sqrt(&r, &na)) { /* a0 = -(r^2) -> sqrt = r*u */
      memset(&o->c0, 0, sizeof(fp_t));
      o->c1 = r;
      return 1;
    }
    return 0;
  }
  fp_t n, t, alpha, delta, half, two_m, x0, x1;
  fp_sqr(&n, &a->c0);
  fp_sqr(&t, &a->c1);
  fp_add(&n, &n, &t);
  if (!fp_sqrt(&alpha, &n)) return 0;
  /* delta = (a0 + alpha)/2 */
  fp_t two_plain = {{2, 0, 0, 0, 0, 0}};
  fp_to_mont(&two_m, &two_plain);
  fp_inv(&half, &two_m);
  fp_add(&delta, &a->c0, &alpha);
  fp_mul(&delta, &delta, &half);
  if (!fp_sqrt(&x0, &delta)) {
    fp_sub(&delta, &a->c0, &alpha);
    fp_mul(&delta, &delta, &half);
    if (!fp_sqrt(&x0, &delta)) return 0;
  }
  fp_t inv2x0;
  fp_dbl(&t, &x0);
  fp_inv(&inv2x0, &t);
  fp_mul(&x1, &a->c1, &inv2x0);
  fp2_t cand = {x0, x1}, chk;
  fp2_sqr(&chk, &cand);
  if (!fp2_eq(&chk, a)) return 0;
  *o = cand;
  return 1;
}

/* ------------------------------------------------------------------ */
/* Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v)                    */
/* ------------------------------------------------------------------ */

typedef struct { fp2_t c0, c1, c2; } fp6_t;
typedef struct { fp6_t c0, c1; } fp12_t;

static void fp6_add(fp6_t *o, const fp6_t *a, const fp6_t *b) {
  fp2_add(&o->c0, &a->c0, &b->c0);
  fp2_add(&o->c1, &a->c1, &b->c1);
  fp2_add(&o->c2, &a->c2, &b->c2);
}

static void fp6_sub(fp6_t *o, const fp6_t *a, const fp6_t *b) {
  fp2_sub(&o->c0, &a->c0, &b->c0);
  fp2_sub(&o->c1, &a->c1, &b->c1);
  fp2_sub(&o->c2, &a->c2, &b->c2);
}

static void fp6_neg(fp6_t *o, const fp6_t *a) {
  fp2_neg(&o->c0, &a->c0);
  fp2_neg(&o->c1, &a->c1);
  fp2_neg(&o->c2, &a->c2);
}

static void fp6_mul(fp6_t *o, const fp6_t *a, const fp6_t *b) {
  fp2_t t0, t1, t2, s, u, r0, r1, r2;
  fp2_mul(&t0, &a->c0, &b->c0);
  fp2_mul(&t1, &a->c1, &b->c1);
  fp2_mul(&t2, &a->c2, &b->c2);
  /* c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2) */
  fp2_add(&s, &a->c1, &a->c2);
  fp2_add(&u, &b->c1, &b->c2);
  fp2_mul(&s, &s, &u);
  fp2_sub(&s, &s, &t1);
  fp2_sub(&s, &s, &t2);
  fp2_mul_by_xi(&s, &s);
  fp2_add(&r0, &t0, &s);
  /* c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2 */
  fp2_add(&s, &a->c0, &a->c1);
  fp2_add(&u, &b->c0, &b->c1);
  fp2_mul(&s, &s, &u);
  fp2_sub(&s, &s, &t0);
  fp2_sub(&s, &s, &t1);
  fp2_t xt2;
  fp2_mul_by_xi(&xt2, &t2);
  fp2_add(&r1, &s, &xt2);
  /* c2 = (a0+a2)(b0+b2) - t0 - t2 + t1 */
  fp2_add(&s, &a->c0, &a->c2);
  fp2_add(&u, &b->c0, &b->c2);
  fp2_mul(&s, &s, &u);
  fp2_sub(&s, &s, &t0);
  fp2_sub(&s, &s, &t2);
  fp2_add(&r2, &s, &t1);
  o->c0 = r0;
  o->c1 = r1;
  o->c2 = r2;
}

static void fp6_sqr(fp6_t *o, const fp6_t *a) { fp6_mul(o, a, a); }

static void fp6_mul_by_v(fp6_t *o, const fp6_t *a) {
  fp2_t t;
  fp2_mul_by_xi(&t, &a->c2);
  fp2_t a0 = a->c0, a1 = a->c1;
  o->c0 = t;
  o->c1 = a0;
  o->c2 = a1;
}

static void fp6_mul_fp2(fp6_t *o, const fp6_t *a, const fp2_t *k) {
  fp2_mul(&o->c0, &a->c0, k);
  fp2_mul(&o->c1, &a->c1, k);
  fp2_mul(&o->c2, &a->c2, k);
}

static void fp6_inv(fp6_t *o, const fp6_t *a) {
  fp2_t c0, c1, c2, t, u;
  fp2_sqr(&c0, &a->c0);
  fp2_mul(&t, &a->c1, &a->c2);
  fp2_mul_by_xi(&t, &t);
  fp2_sub(&c0, &c0, &t); /* a0^2 - xi a1 a2 */
  fp2_sqr(&c1, &a->c2);
  fp2_mul_by_xi(&c1, &c1);
  fp2_mul(&t, &a->c0, &a->c1);
  fp2_sub(&c1, &c1, &t); /* xi a2^2 - a0 a1 */
  fp2_sqr(&c2, &a->c1);
  fp2_mul(&t, &a->c0, &a->c2);
  fp2_sub(&c2, &c2, &t); /* a1^2 - a0 a2 */
  /* norm = a0 c0 + xi(a2 c1 + a1 c2) */
  fp2_mul(&t, &a->c2, &c1);
  fp2_mul(&u, &a->c1, &c2);
  fp2_add(&t, &t, &u);
  fp2_mul_by_xi(&t, &t);
  fp2_mul(&u, &a->c0, &c0);
  fp2_add(&t, &t, &u);
  fp2_inv(&t, &t);
  fp2_mul(&o->c0, &c0, &t);
  fp2_mul(&o->c1, &c1, &t);
  fp2_mul(&o->c2, &c2, &t);
}

static void fp12_mul(fp12_t *o, const fp12_t *a, const fp12_t *b) {
  fp6_t t0, t1, s, u, r0;
  fp6_mul(&t0, &a->c0, &b->c0);
  fp6_mul(&t1, &a->c1, &b->c1);
  fp6_mul_by_v(&r0, &t1);
  fp6_add(&r0, &r0, &t0); /* c0 = t0 + v t1 */
  fp6_add(&s, &a->c0, &a->c1);
  fp6_add(&u, &b->c0, &b->c1);
  fp6_mul(&s, &s, &u);
  fp6_sub(&s, &s, &t0);
  fp6_sub(&s, &s, &t1); /* c1 */
  o->c0 = r0;
  o->c1 = s;
}

static void fp12_sqr(fp12_t *o, const fp12_t *a) { fp12_mul(o, a, a); }

static void fp12_conj(fp12_t *o, const fp12_t *a) {
  o->c0 = a->c0;
  fp6_neg(&o->c1, &a->c1);
}

static void fp12_inv(fp12_t *o, const fp12_t *a) {
  fp6_t t0, t1;
  fp6_sqr(&t0, &a->c0);
  fp6_sqr(&t1, &a->c1);
  fp6_mul_by_v(&t1, &t1);
  fp6_sub(&t0, &t0, &t1); /* a0^2 - v a1^2 */
  fp6_inv(&t0, &t0);
  fp6_mul(&o->c0, &a->c0, &t0);
  fp6_t n;
  fp6_neg(&n, &a->c1);
  fp6_mul(&o->c1, &n, &t0);
}

static void fp12_one(fp12_t *o) {
  memset(o, 0, sizeof(*o));
  o->c0.c0.c0 = FP_ONE_M;
}

static int fp12_is_one(const fp12_t *a) {
  fp12_t one;
  fp12_one(&one);
  return memcmp(a, &one, sizeof(one)) == 0 ||
         (fp_eq(&a->c0.c0.c0, &FP_ONE_M) && fp_is_zero(&a->c0.c0.c1) &&
          fp2_is_zero(&a->c0.c1) && fp2_is_zero(&a->c0.c2) &&
          fp2_is_zero(&a->c1.c0) && fp2_is_zero(&a->c1.c1) &&
          fp2_is_zero(&a->c1.c2));
}

static void fp6_frobenius(fp6_t *o, const fp6_t *a) {
  /* (v^i)^p = v^i * XI^(i(p-1)/3) = v^i * FROB6_C1[i] */
  fp2_conj(&o->c0, &a->c0);
  fp2_t t;
  fp2_conj(&t, &a->c1);
  fp2_mul(&o->c1, &t, &FROB6_C1[1]);
  fp2_conj(&t, &a->c2);
  fp2_mul(&o->c2, &t, &FROB6_C1[2]);
}

static void fp12_frobenius(fp12_t *o, const fp12_t *a) {
  fp6_frobenius(&o->c0, &a->c0);
  fp6_t t;
  fp6_frobenius(&t, &a->c1);
  fp6_mul_fp2(&o->c1, &t, &FROB12_C1);
}

static void fp12_frobenius_n(fp12_t *o, const fp12_t *a, int n) {
  *o = *a;
  for (int i = 0; i < n; i++) fp12_frobenius(o, o);
}

/* ------------------------------------------------------------------ */
/* Curves: G1 over Fp (b=4), G2 over Fp2 on the M-twist (b=4(1+u))     */
/* ------------------------------------------------------------------ */

typedef struct { fp_t x, y, z; int inf; } g1_t;
typedef struct { fp2_t x, y, z; int inf; } g2_t;

#define DEFINE_CURVE(NAME, FE, FE_ADD, FE_SUB, FE_MUL, FE_SQR, FE_DBL,  \
                     FE_NEG, FE_IS_ZERO, FE_EQ, FE_INV, PT)             \
  static void NAME##_dbl(PT *o, const PT *p) {                          \
    if (p->inf) { *o = *p; return; }                                    \
    FE a, b, c, d, e, f, t, x3, y3, z3;                                 \
    FE_SQR(&a, &p->x);                                                  \
    FE_SQR(&b, &p->y);                                                  \
    FE_SQR(&c, &b);                                                     \
    FE_ADD(&t, &p->x, &b);                                              \
    FE_SQR(&t, &t);                                                     \
    FE_SUB(&t, &t, &a);                                                 \
    FE_SUB(&t, &t, &c);                                                 \
    FE_DBL(&d, &t); /* d = 2((x+b)^2 - a - c) */                        \
    FE_ADD(&e, &a, &a);                                                 \
    FE_ADD(&e, &e, &a); /* e = 3a */                                    \
    FE_SQR(&f, &e);                                                     \
    FE_DBL(&t, &d);                                                     \
    FE_SUB(&x3, &f, &t);                                                \
    FE_SUB(&t, &d, &x3);                                                \
    FE_MUL(&t, &e, &t);                                                 \
    FE c8;                                                              \
    FE_DBL(&c8, &c);                                                    \
    FE_DBL(&c8, &c8);                                                   \
    FE_DBL(&c8, &c8);                                                   \
    FE_SUB(&y3, &t, &c8);                                               \
    FE_MUL(&z3, &p->y, &p->z);                                          \
    FE_DBL(&z3, &z3);                                                   \
    o->x = x3; o->y = y3; o->z = z3; o->inf = 0;                        \
  }                                                                     \
  static void NAME##_add(PT *o, const PT *p, const PT *q) {             \
    if (p->inf) { *o = *q; return; }                                    \
    if (q->inf) { *o = *p; return; }                                    \
    FE z1z1, z2z2, u1, u2, s1, s2, h, r, t;                             \
    FE_SQR(&z1z1, &p->z);                                               \
    FE_SQR(&z2z2, &q->z);                                               \
    FE_MUL(&u1, &p->x, &z2z2);                                          \
    FE_MUL(&u2, &q->x, &z1z1);                                          \
    FE_MUL(&s1, &p->y, &q->z);                                          \
    FE_MUL(&s1, &s1, &z2z2);                                            \
    FE_MUL(&s2, &q->y, &p->z);                                          \
    FE_MUL(&s2, &s2, &z1z1);                                            \
    FE_SUB(&h, &u2, &u1);                                               \
    FE_SUB(&r, &s2, &s1);                                               \
    if (FE_IS_ZERO(&h)) {                                               \
      if (FE_IS_ZERO(&r)) { NAME##_dbl(o, p); return; }                 \
      o->inf = 1; return;                                               \
    }                                                                   \
    FE h2, h3, u1h2, x3, y3, z3;                                        \
    FE_SQR(&h2, &h);                                                    \
    FE_MUL(&h3, &h2, &h);                                               \
    FE_MUL(&u1h2, &u1, &h2);                                            \
    FE_SQR(&x3, &r);                                                    \
    FE_SUB(&x3, &x3, &h3);                                              \
    FE_DBL(&t, &u1h2);                                                  \
    FE_SUB(&x3, &x3, &t);                                               \
    FE_SUB(&t, &u1h2, &x3);                                             \
    FE_MUL(&t, &r, &t);                                                 \
    FE s1h3;                                                            \
    FE_MUL(&s1h3, &s1, &h3);                                            \
    FE_SUB(&y3, &t, &s1h3);                                             \
    FE_MUL(&z3, &p->z, &q->z);                                          \
    FE_MUL(&z3, &z3, &h);                                               \
    o->x = x3; o->y = y3; o->z = z3; o->inf = 0;                        \
  }                                                                     \
  static void NAME##_mul_be(PT *o, const PT *p, const uint8_t *scalar,  \
                            int nbytes) {                               \
    PT acc;                                                             \
    acc.inf = 1;                                                        \
    for (int i = 0; i < nbytes; i++) {                                  \
      uint8_t byte = scalar[i];                                         \
      for (int b = 7; b >= 0; b--) {                                    \
        NAME##_dbl(&acc, &acc);                                         \
        if ((byte >> b) & 1) NAME##_add(&acc, &acc, p);                 \
      }                                                                 \
    }                                                                   \
    *o = acc;                                                           \
  }                                                                     \
  static void NAME##_mul_limbs(PT *o, const PT *p, const uint64_t *e,   \
                               int nlimbs) {                            \
    PT acc;                                                             \
    acc.inf = 1;                                                        \
    for (int i = nlimbs * 64 - 1; i >= 0; i--) {                        \
      NAME##_dbl(&acc, &acc);                                           \
      if ((e[i / 64] >> (i % 64)) & 1) NAME##_add(&acc, &acc, p);       \
    }                                                                   \
    *o = acc;                                                           \
  }                                                                     \
  static void NAME##_to_affine(PT *o, const PT *p) {                    \
    if (p->inf) { *o = *p; return; }                                    \
    FE zi, zi2, zi3;                                                    \
    FE_INV(&zi, &p->z);                                                 \
    FE_SQR(&zi2, &zi);                                                  \
    FE_MUL(&zi3, &zi2, &zi);                                            \
    FE_MUL(&o->x, &p->x, &zi2);                                         \
    FE_MUL(&o->y, &p->y, &zi3);                                         \
    o->z = zi; /* unused marker */                                      \
    o->inf = 0;                                                         \
  }

DEFINE_CURVE(g1, fp_t, fp_add, fp_sub, fp_mul, fp_sqr, fp_dbl, fp_neg,
             fp_is_zero, fp_eq, fp_inv, g1_t)
DEFINE_CURVE(g2, fp2_t, fp2_add, fp2_sub, fp2_mul, fp2_sqr, fp2_dbl,
             fp2_neg, fp2_is_zero, fp2_eq, fp2_inv, g2_t)

static void g1_set_affine(g1_t *o, const fp_t *x, const fp_t *y) {
  o->x = *x;
  o->y = *y;
  o->z = FP_ONE_M;
  o->inf = 0;
}

static void g2_set_affine(g2_t *o, const fp2_t *x, const fp2_t *y) {
  o->x = *x;
  o->y = *y;
  o->z.c0 = FP_ONE_M;
  memset(&o->z.c1, 0, sizeof(fp_t));
  o->inf = 0;
}

static int g1_on_curve_affine(const fp_t *x, const fp_t *y) {
  fp_t l, r;
  fp_sqr(&l, y);
  fp_sqr(&r, x);
  fp_mul(&r, &r, x);
  fp_add(&r, &r, &FP_B_M);
  return fp_eq(&l, &r);
}

static int g2_on_curve_affine(const fp2_t *x, const fp2_t *y) {
  fp2_t l, r, b;
  fp2_sqr(&l, y);
  fp2_sqr(&r, x);
  fp2_mul(&r, &r, x);
  /* b' = 4(1+u) */
  b.c0 = FP_B_M;
  b.c1 = FP_B_M;
  fp2_add(&r, &r, &b);
  return fp2_eq(&l, &r);
}

/* ------------------------------------------------------------------ */
/* Fast subgroup checks + cofactor clearing via the psi endomorphism   */
/* (untwist-Frobenius-twist; Bowe, "Faster subgroup checks for        */
/* BLS12-381"; RFC 9380 App. G.4). On G2, psi acts as [x]; checking   */
/* psi(Q) == [x]Q costs one 64-bit ladder instead of a 255-bit        */
/* order multiplication. The psi/phi coefficients are DERIVED AT      */
/* LOAD TIME from field exponentiations and validated against the     */
/* generators; if validation fails the slow order-multiplication      */
/* paths stay in force, so correctness never depends on the derive.   */
/* ------------------------------------------------------------------ */

static fp2_t PSI_CX, PSI_CY; /* psi: (x,y) -> (CX*conj(x), CY*conj(y)) */
static fp_t G1_BETA;         /* phi: (x,y) -> (BETA*x, y) */
static int PSI_READY = 0;
static int G1_PHI_READY = 0;
static int G1_PHI_NEG = 0; /* 1: phi(P) == -[x^2]P; 0: phi(P) == [x^2-1]P */

static void fp2_pow_limbs(fp2_t *o, const fp2_t *a, const uint64_t *e,
                          int nlimbs) {
  fp2_t acc, base = *a;
  memset(&acc, 0, sizeof(acc));
  acc.c0 = FP_ONE_M;
  int top = nlimbs * 64 - 1;
  while (top >= 0 && !((e[top / 64] >> (top % 64)) & 1)) top--;
  for (int i = top; i >= 0; i--) {
    fp2_sqr(&acc, &acc);
    if ((e[i / 64] >> (i % 64)) & 1) fp2_mul(&acc, &acc, &base);
  }
  *o = acc;
}

static void g1_neg_pt(g1_t *o, const g1_t *p) {
  *o = *p;
  fp_neg(&o->y, &p->y);
}

static void g2_neg_pt(g2_t *o, const g2_t *p) {
  *o = *p;
  fp2_neg(&o->y, &p->y);
}

static int g1_eq_jac(const g1_t *a, const g1_t *b) {
  if (a->inf || b->inf) return a->inf && b->inf;
  fp_t za2, zb2, l, r, za3, zb3;
  fp_sqr(&za2, &a->z);
  fp_sqr(&zb2, &b->z);
  fp_mul(&l, &a->x, &zb2);
  fp_mul(&r, &b->x, &za2);
  if (!fp_eq(&l, &r)) return 0;
  fp_mul(&za3, &za2, &a->z);
  fp_mul(&zb3, &zb2, &b->z);
  fp_mul(&l, &a->y, &zb3);
  fp_mul(&r, &b->y, &za3);
  return fp_eq(&l, &r);
}

static int g2_eq_jac(const g2_t *a, const g2_t *b) {
  if (a->inf || b->inf) return a->inf && b->inf;
  fp2_t za2, zb2, l, r, za3, zb3;
  fp2_sqr(&za2, &a->z);
  fp2_sqr(&zb2, &b->z);
  fp2_mul(&l, &a->x, &zb2);
  fp2_mul(&r, &b->x, &za2);
  if (!fp2_eq(&l, &r)) return 0;
  fp2_mul(&za3, &za2, &a->z);
  fp2_mul(&zb3, &zb2, &b->z);
  fp2_mul(&l, &a->y, &zb3);
  fp2_mul(&r, &b->y, &za3);
  return fp2_eq(&l, &r);
}

/* psi on Jacobian coords: affine x = X/Z^2, so (X,Y,Z) ->
   (CX*conj(X), CY*conj(Y), conj(Z)) represents (CX*conj(x), CY*conj(y)). */
static void g2_psi(g2_t *o, const g2_t *p) {
  fp2_conj(&o->x, &p->x);
  fp2_conj(&o->y, &p->y);
  fp2_conj(&o->z, &p->z);
  fp2_mul(&o->x, &o->x, &PSI_CX);
  fp2_mul(&o->y, &o->y, &PSI_CY);
  o->inf = p->inf;
}

/* [x]P for the (negative) BLS parameter x: |x| ladder then negate. */
static void g2_mul_x(g2_t *o, const g2_t *p) {
  g2_mul_limbs(o, p, &BLS_X_ABS, 1);
  g2_neg_pt(o, o);
}

static void psi_init(void) {
  /* exponents (p-1)/2 and (p-1)/3 from FP_P (p-1 is even; p = 1 mod 3) */
  uint64_t pm1[6], e2[6], e3[6];
  uint64_t borrow = 1;
  for (int i = 0; i < 6; i++) {
    pm1[i] = FP_P.l[i] - borrow;
    borrow = (borrow && FP_P.l[i] == 0) ? 1 : 0;
  }
  for (int i = 0; i < 6; i++)
    e2[i] = (pm1[i] >> 1) | (i + 1 < 6 ? pm1[i + 1] << 63 : 0);
  u128 rem = 0;
  for (int i = 5; i >= 0; i--) {
    u128 cur = (rem << 64) | pm1[i];
    e3[i] = (uint64_t)(cur / 3);
    rem = cur % 3;
  }
  /* candidate coefficients from xi = 1+u */
  fp2_t xi, a3, a2, i3, i2;
  xi.c0 = FP_ONE_M;
  xi.c1 = FP_ONE_M;
  fp2_pow_limbs(&a3, &xi, e3, 6); /* (1+u)^((p-1)/3) */
  fp2_pow_limbs(&a2, &xi, e2, 6); /* (1+u)^((p-1)/2) */
  fp2_inv(&i3, &a3);
  fp2_inv(&i2, &a2);
  /* select the pair that satisfies psi(G2_GEN) == [x]G2_GEN */
  g2_t gen, xg, pg;
  g2_set_affine(&gen, &G2_GEN_X, &G2_GEN_Y);
  g2_mul_x(&xg, &gen);
  const fp2_t *cx[4] = {&i3, &i3, &a3, &a3};
  const fp2_t *cy[4] = {&i2, &a2, &i2, &a2};
  for (int k = 0; k < 4; k++) {
    PSI_CX = *cx[k];
    PSI_CY = *cy[k];
    g2_psi(&pg, &gen);
    if (g2_eq_jac(&pg, &xg)) {
      PSI_READY = 1;
      break;
    }
  }
  /* G1 phi: beta = nontrivial cube root of unity; eigenvalue is
     x^2-1 or -x^2 depending on which root — select on the generator. */
  fp_t two, beta, cand;
  fp_add(&two, &FP_ONE_M, &FP_ONE_M);
  fp_pow(&beta, &two, e3, 6); /* 2^((p-1)/3) */
  if (fp_eq(&beta, &FP_ONE_M)) {
    fp_t three;
    fp_add(&three, &two, &FP_ONE_M);
    fp_pow(&beta, &three, e3, 6);
  }
  g1_t g1gen, t, x2g, r, phi;
  g1_set_affine(&g1gen, &G1_GEN_X, &G1_GEN_Y);
  g1_mul_limbs(&t, &g1gen, &BLS_X_ABS, 1);
  g1_mul_limbs(&x2g, &t, &BLS_X_ABS, 1); /* [x^2]gen (sign squares away) */
  cand = beta;
  for (int k = 0; k < 2 && !G1_PHI_READY; k++) {
    phi = g1gen;
    fp_mul(&phi.x, &phi.x, &cand);
    g1_t ng, res;
    g1_neg_pt(&ng, &g1gen);
    g1_add(&res, &x2g, &ng); /* [x^2-1]gen */
    if (g1_eq_jac(&phi, &res)) {
      G1_BETA = cand;
      G1_PHI_READY = 1;
      G1_PHI_NEG = 0;
      break;
    }
    g1_neg_pt(&res, &x2g); /* -[x^2]gen */
    if (g1_eq_jac(&phi, &res)) {
      G1_BETA = cand;
      G1_PHI_READY = 1;
      G1_PHI_NEG = 1;
      break;
    }
    fp_sqr(&cand, &beta); /* the other root */
  }
}

__attribute__((constructor)) static void blsn_init(void) { psi_init(); }

static int g1_in_subgroup(const g1_t *p) {
  if (p->inf) return 1;
  if (!G1_PHI_READY) {
    g1_t t;
    g1_mul_limbs(&t, p, BLS_R, 4);
    return t.inf;
  }
  g1_t t, x2p, r, phi;
  g1_mul_limbs(&t, p, &BLS_X_ABS, 1);
  g1_mul_limbs(&x2p, &t, &BLS_X_ABS, 1);
  if (G1_PHI_NEG) {
    g1_neg_pt(&r, &x2p);
  } else {
    g1_t np;
    g1_neg_pt(&np, p);
    g1_add(&r, &x2p, &np);
  }
  phi = *p;
  fp_mul(&phi.x, &phi.x, &G1_BETA);
  return g1_eq_jac(&phi, &r);
}

static int g2_in_subgroup(const g2_t *p) {
  if (p->inf) return 1;
  if (!PSI_READY) {
    g2_t t;
    g2_mul_limbs(&t, p, BLS_R, 4);
    return t.inf;
  }
  g2_t xp, pg;
  g2_mul_x(&xp, p);
  g2_psi(&pg, p);
  return g2_eq_jac(&pg, &xp);
}

/* RFC 9380 App. G.4: h_eff*P as (x^2-x-1)P + (x-1)psi(P) + psi^2(2P) */
static void g2_clear_cofactor_fast(g2_t *o, const g2_t *p) {
  g2_t t1, t2, t3, tmp;
  g2_mul_x(&t1, p);  /* t1 = [x]P */
  g2_psi(&t2, p);    /* t2 = psi(P) */
  g2_dbl(&t3, p);
  g2_psi(&t3, &t3);
  g2_psi(&t3, &t3);  /* t3 = psi^2(2P) */
  g2_neg_pt(&tmp, &t2);
  g2_add(&t3, &t3, &tmp); /* t3 -= t2 */
  g2_add(&t2, &t1, &t2);  /* t2 = t1 + psi(P) */
  g2_mul_x(&t2, &t2);     /* t2 = [x^2]P + [x]psi(P) */
  g2_add(&t3, &t3, &t2);
  g2_neg_pt(&tmp, &t1);
  g2_add(&t3, &t3, &tmp); /* t3 -= t1 */
  g2_neg_pt(&tmp, p);
  g2_add(o, &t3, &tmp); /* Q = t3 - P */
}

/* ------------------------------------------------------------------ */
/* Pairing: optimal ate, Miller loop on the twist with sparse lines    */
/* (same line formulas as lodestar_tpu/ops/pairing.py:_dbl_step/_add)  */
/* ------------------------------------------------------------------ */

/* multiply f by the sparse line l0 + l2 w^2 + l3 w^3
   (slots: c0.c0 += l0, c0.c1 += l2, c1.c1 += l3) */
static void fp12_mul_by_line(fp12_t *o, const fp12_t *f, const fp2_t *l0,
                             const fp2_t *l2, const fp2_t *l3) {
  fp12_t line;
  memset(&line, 0, sizeof(line));
  line.c0.c0 = *l0;
  line.c0.c1 = *l2;
  line.c1.c1 = *l3;
  fp12_mul(o, f, &line);
}

static void miller_dbl_step(g2_t *T, const fp_t *px, const fp_t *py,
                            fp2_t *l0, fp2_t *l2, fp2_t *l3) {
  fp2_t A, B, C, Z2, XA, YZ, t, D, E, F2, x3, y3, z3;
  fp2_sqr(&A, &T->x);
  fp2_sqr(&B, &T->y);
  fp2_sqr(&C, &B);
  fp2_sqr(&Z2, &T->z);
  fp2_mul(&XA, &T->x, &A); /* X^3 */
  fp2_mul(&YZ, &T->y, &T->z);
  /* l0 = 3X^3 - 2Y^2 */
  fp2_dbl(&t, &XA);
  fp2_add(&t, &t, &XA);
  fp2_t twoB;
  fp2_dbl(&twoB, &B);
  fp2_sub(l0, &t, &twoB);
  /* l2 = -3 X^2 Z^2 * px */
  fp2_mul(&t, &A, &Z2);
  fp2_dbl(l2, &t);
  fp2_add(l2, l2, &t);
  fp2_neg(l2, l2);
  fp2_mul_fp(l2, l2, px);
  /* l3 = 2 Y Z^3 * py */
  fp2_mul(&t, &YZ, &Z2);
  fp2_dbl(l3, &t);
  fp2_mul_fp(l3, l3, py);
  /* point doubling (dbl-2009-l) */
  fp2_add(&t, &T->x, &B);
  fp2_sqr(&t, &t);
  fp2_sub(&t, &t, &A);
  fp2_sub(&t, &t, &C);
  fp2_dbl(&D, &t);
  fp2_dbl(&E, &A);
  fp2_add(&E, &E, &A);
  fp2_sqr(&F2, &E);
  fp2_dbl(&t, &D);
  fp2_sub(&x3, &F2, &t);
  fp2_sub(&t, &D, &x3);
  fp2_mul(&t, &E, &t);
  fp2_t c8;
  fp2_dbl(&c8, &C);
  fp2_dbl(&c8, &c8);
  fp2_dbl(&c8, &c8);
  fp2_sub(&y3, &t, &c8);
  fp2_dbl(&z3, &YZ);
  T->x = x3;
  T->y = y3;
  T->z = z3;
}

static void miller_add_step(g2_t *T, const fp2_t *qx, const fp2_t *qy,
                            const fp_t *px, const fp_t *py, fp2_t *l0,
                            fp2_t *l2, fp2_t *l3) {
  fp2_t Z2, Z3, mu, th, Zmu, t, u;
  fp2_sqr(&Z2, &T->z);
  fp2_mul(&Z3, &Z2, &T->z);
  fp2_mul(&mu, qx, &Z2);
  fp2_sub(&mu, &mu, &T->x);
  fp2_mul(&th, qy, &Z3);
  fp2_sub(&th, &th, &T->y);
  fp2_mul(&Zmu, &T->z, &mu);
  /* l0 = th*qx - Zmu*qy */
  fp2_mul(&t, &th, qx);
  fp2_mul(&u, &Zmu, qy);
  fp2_sub(l0, &t, &u);
  /* l2 = -th * px */
  fp2_neg(&t, &th);
  fp2_mul_fp(l2, &t, px);
  /* l3 = Zmu * py */
  fp2_mul_fp(l3, &Zmu, py);
  /* point mixed add */
  fp2_t mu2, mu3, xmu2, x3, y3;
  fp2_sqr(&mu2, &mu);
  fp2_mul(&mu3, &mu2, &mu);
  fp2_mul(&xmu2, &T->x, &mu2);
  fp2_sqr(&x3, &th);
  fp2_sub(&x3, &x3, &mu3);
  fp2_dbl(&t, &xmu2);
  fp2_sub(&x3, &x3, &t);
  fp2_sub(&t, &xmu2, &x3);
  fp2_mul(&t, &th, &t);
  fp2_mul(&u, &T->y, &mu3);
  fp2_sub(&y3, &t, &u);
  T->x = x3;
  T->y = y3;
  T->z = Zmu;
}

/* accumulate one (P, Q) pair into f (both affine, not infinity) */
static void miller_loop_acc(fp12_t *f, const fp_t *px, const fp_t *py,
                            const fp2_t *qx, const fp2_t *qy) {
  g2_t T;
  g2_set_affine(&T, qx, qy);
  fp12_t acc;
  fp12_one(&acc);
  fp2_t l0, l2, l3;
  /* MSB-first over |x| after the top bit */
  for (int i = 62; i >= 0; i--) {
    fp12_sqr(&acc, &acc);
    miller_dbl_step(&T, px, py, &l0, &l2, &l3);
    fp12_mul_by_line(&acc, &acc, &l0, &l2, &l3);
    if ((BLS_X_ABS >> i) & 1) {
      miller_add_step(&T, qx, qy, px, py, &l0, &l2, &l3);
      fp12_mul_by_line(&acc, &acc, &l0, &l2, &l3);
    }
  }
  /* x < 0: conjugate */
  fp12_conj(&acc, &acc);
  fp12_mul(f, f, &acc);
}

/* f^|x| by square-and-multiply (cheap in C) */
static void fp12_pow_u(fp12_t *o, const fp12_t *a) {
  fp12_t r, base = *a;
  fp12_one(&r);
  for (int i = 63; i >= 0; i--) {
    fp12_sqr(&r, &r);
    if ((BLS_X_ABS >> i) & 1) fp12_mul(&r, &r, &base);
  }
  *o = r;
}

static void fp12_pow_x_minus_1(fp12_t *o, const fp12_t *a) {
  fp12_t t;
  fp12_pow_u(&t, a);
  fp12_mul(&t, &t, a);
  fp12_conj(o, &t); /* x negative, unitary input */
}

static void final_exponentiation(fp12_t *o, const fp12_t *f) {
  /* easy: t = f^((q^6-1)(q^2+1)) */
  fp12_t t, inv, u;
  fp12_conj(&t, f);
  fp12_inv(&inv, f);
  fp12_mul(&t, &t, &inv);
  fp12_frobenius_n(&u, &t, 2);
  fp12_mul(&t, &u, &t);
  /* hard (cubed map, same chain as ops/pairing.py): */
  fp12_t a, b, c, t2;
  fp12_pow_x_minus_1(&a, &t);
  fp12_pow_x_minus_1(&a, &a);
  fp12_pow_u(&b, &a);
  fp12_conj(&b, &b); /* a^x */
  fp12_frobenius_n(&u, &a, 1);
  fp12_mul(&b, &b, &u);
  fp12_pow_u(&c, &b);
  fp12_conj(&c, &c);
  fp12_pow_u(&c, &c);
  fp12_conj(&c, &c); /* b^(x^2) */
  fp12_frobenius_n(&u, &b, 2);
  fp12_mul(&c, &c, &u);
  fp12_conj(&u, &b);
  fp12_mul(&c, &c, &u);
  fp12_sqr(&t2, &t);
  fp12_mul(&c, &c, &t2);
  fp12_mul(o, &c, &t);
}

/* ------------------------------------------------------------------ */
/* SHA-256 (for expand_message_xmd)                                    */
/* ------------------------------------------------------------------ */

static const uint32_t sha_k[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

typedef struct {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t len;
  uint32_t buflen;
} sha256_ctx;

static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

static void sha256_block(sha256_ctx *c, const uint8_t *p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
           ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + sha_k[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void sha256_init(sha256_ctx *c) {
  static const uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  memcpy(c->h, iv, sizeof(iv));
  c->len = 0;
  c->buflen = 0;
}

static void sha256_update(sha256_ctx *c, const uint8_t *p, uint64_t n) {
  c->len += n;
  while (n) {
    uint32_t take = 64 - c->buflen;
    if (take > n) take = (uint32_t)n;
    memcpy(c->buf + c->buflen, p, take);
    c->buflen += take;
    p += take;
    n -= take;
    if (c->buflen == 64) {
      sha256_block(c, c->buf);
      c->buflen = 0;
    }
  }
}

static void sha256_final(sha256_ctx *c, uint8_t out[32]) {
  uint64_t bits = c->len * 8;
  uint8_t pad = 0x80;
  sha256_update(c, &pad, 1);
  uint8_t z = 0;
  while (c->buflen != 56) sha256_update(c, &z, 1);
  uint8_t lb[8];
  for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (56 - 8 * i));
  sha256_update(c, lb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (uint8_t)(c->h[i] >> 24);
    out[4 * i + 1] = (uint8_t)(c->h[i] >> 16);
    out[4 * i + 2] = (uint8_t)(c->h[i] >> 8);
    out[4 * i + 3] = (uint8_t)(c->h[i]);
  }
}

/* ------------------------------------------------------------------ */
/* hash_to_curve G2 (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_)         */
/* ------------------------------------------------------------------ */

static void expand_message_xmd(const uint8_t *msg, uint32_t msg_len,
                               const uint8_t *dst, uint32_t dst_len,
                               uint8_t *out, uint32_t len_in_bytes) {
  uint32_t ell = (len_in_bytes + 31) / 32;
  uint8_t b0[32], bi[32];
  sha256_ctx c;
  sha256_init(&c);
  uint8_t zpad[64] = {0};
  sha256_update(&c, zpad, 64);
  sha256_update(&c, msg, msg_len);
  uint8_t lib[3] = {(uint8_t)(len_in_bytes >> 8), (uint8_t)len_in_bytes, 0};
  sha256_update(&c, lib, 3);
  sha256_update(&c, dst, dst_len);
  uint8_t dlen = (uint8_t)dst_len;
  sha256_update(&c, &dlen, 1);
  sha256_final(&c, b0);

  uint8_t prev[32];
  for (uint32_t i = 1; i <= ell; i++) {
    sha256_init(&c);
    if (i == 1) {
      sha256_update(&c, b0, 32);
    } else {
      uint8_t x[32];
      for (int j = 0; j < 32; j++) x[j] = b0[j] ^ prev[j];
      sha256_update(&c, x, 32);
    }
    uint8_t ib = (uint8_t)i;
    sha256_update(&c, &ib, 1);
    sha256_update(&c, dst, dst_len);
    sha256_update(&c, &dlen, 1);
    sha256_final(&c, bi);
    memcpy(prev, bi, 32);
    uint32_t off = (i - 1) * 32;
    uint32_t take = len_in_bytes - off < 32 ? len_in_bytes - off : 32;
    memcpy(out + off, bi, take);
  }
}

static void map_to_curve_sswu(g2_t *o, const fp2_t *u) {
  fp2_t u2, zu2, tv, x1, gx, y, t, negb, inva;
  fp2_sqr(&u2, u);
  fp2_mul(&zu2, &SSWU_Z, &u2);
  fp2_sqr(&tv, &zu2);
  fp2_add(&tv, &tv, &zu2);
  if (fp2_is_zero(&tv)) {
    /* x1 = B / (Z*A) */
    fp2_t za;
    fp2_mul(&za, &SSWU_Z, &SSWU_A);
    fp2_inv(&za, &za);
    fp2_mul(&x1, &SSWU_B, &za);
  } else {
    fp2_t tv1, one;
    fp2_inv(&tv1, &tv);
    memset(&one, 0, sizeof(one));
    one.c0 = FP_ONE_M;
    fp2_add(&tv1, &tv1, &one);
    fp2_neg(&negb, &SSWU_B);
    fp2_inv(&inva, &SSWU_A);
    fp2_mul(&x1, &negb, &inva);
    fp2_mul(&x1, &x1, &tv1);
  }
  /* g(x) = (x^2 + A) x + B */
  fp2_sqr(&gx, &x1);
  fp2_add(&gx, &gx, &SSWU_A);
  fp2_mul(&gx, &gx, &x1);
  fp2_add(&gx, &gx, &SSWU_B);
  fp2_t x = x1;
  if (!fp2_sqrt(&y, &gx)) {
    fp2_mul(&x, &zu2, &x1);
    fp2_sqr(&gx, &x);
    fp2_add(&gx, &gx, &SSWU_A);
    fp2_mul(&gx, &gx, &x);
    fp2_add(&gx, &gx, &SSWU_B);
    fp2_sqrt(&y, &gx); /* guaranteed */
  }
  if (fp2_sgn0(u) != fp2_sgn0(&y)) fp2_neg(&y, &y);
  g2_set_affine(o, &x, &y);
}

static void horner(fp2_t *o, const fp2_t *coeffs, int n, const fp2_t *x) {
  fp2_t acc = coeffs[n - 1];
  for (int i = n - 2; i >= 0; i--) {
    fp2_mul(&acc, &acc, x);
    fp2_add(&acc, &acc, &coeffs[i]);
  }
  *o = acc;
}

static void iso_map_g2(g2_t *o, const g2_t *p) {
  /* p affine on E2' */
  fp2_t xn, xd, yn, yd, t;
  horner(&xn, ISO_XNUM, 4, &p->x);
  horner(&xd, ISO_XDEN, 3, &p->x);
  horner(&yn, ISO_YNUM, 4, &p->x);
  horner(&yd, ISO_YDEN, 4, &p->x);
  fp2_t xo, yo;
  fp2_inv(&t, &xd);
  fp2_mul(&xo, &xn, &t);
  fp2_inv(&t, &yd);
  fp2_mul(&yo, &yn, &t);
  fp2_mul(&yo, &yo, &p->y);
  g2_set_affine(o, &xo, &yo);
}

static void hash_to_g2_point(g2_t *o, const uint8_t *msg, uint32_t msg_len,
                             const uint8_t *dst, uint32_t dst_len) {
  uint8_t uniform[256];
  expand_message_xmd(msg, msg_len, dst, dst_len, uniform, 256);
  fp2_t u0, u1;
  fp_from_bytes_wide(&u0.c0, uniform);
  fp_from_bytes_wide(&u0.c1, uniform + 64);
  fp_from_bytes_wide(&u1.c0, uniform + 128);
  fp_from_bytes_wide(&u1.c1, uniform + 192);
  g2_t q0, q1, q0m, q1m, sum;
  map_to_curve_sswu(&q0, &u0);
  map_to_curve_sswu(&q1, &u1);
  iso_map_g2(&q0m, &q0);
  iso_map_g2(&q1m, &q1);
  g2_add(&sum, &q0m, &q1m);
  if (PSI_READY) {
    g2_clear_cofactor_fast(o, &sum);
  } else {
    g2_mul_limbs(o, &sum, G2_H_EFF, G2_H_EFF_LIMBS);
  }
}

/* ------------------------------------------------------------------ */
/* Byte ABI                                                            */
/* ------------------------------------------------------------------ */

static int is_zero_bytes(const uint8_t *p, int n) {
  uint8_t r = 0;
  for (int i = 0; i < n; i++) r |= p[i];
  return r == 0;
}

static int g1_from_affine_bytes(g1_t *o, const uint8_t in[96]) {
  if (is_zero_bytes(in, 96)) {
    o->inf = 1;
    return 1;
  }
  fp_t x, y;
  if (!fp_from_bytes(&x, in) || !fp_from_bytes(&y, in + 48)) return 0;
  if (!g1_on_curve_affine(&x, &y)) return 0;
  g1_set_affine(o, &x, &y);
  return 1;
}

static void g1_to_affine_bytes(uint8_t out[96], const g1_t *p) {
  if (p->inf) {
    memset(out, 0, 96);
    return;
  }
  g1_t a;
  g1_to_affine(&a, p);
  fp_to_bytes(out, &a.x);
  fp_to_bytes(out + 48, &a.y);
}

static int g2_from_affine_bytes(g2_t *o, const uint8_t in[192]) {
  if (is_zero_bytes(in, 192)) {
    o->inf = 1;
    return 1;
  }
  fp2_t x, y;
  /* layout: x.c1 || x.c0 || y.c1 || y.c0 (BE, matching compressed order) */
  if (!fp_from_bytes(&x.c1, in) || !fp_from_bytes(&x.c0, in + 48) ||
      !fp_from_bytes(&y.c1, in + 96) || !fp_from_bytes(&y.c0, in + 144))
    return 0;
  if (!g2_on_curve_affine(&x, &y)) return 0;
  g2_set_affine(o, &x, &y);
  return 1;
}

static void g2_to_affine_bytes(uint8_t out[192], const g2_t *p) {
  if (p->inf) {
    memset(out, 0, 192);
    return;
  }
  g2_t a;
  g2_to_affine(&a, p);
  fp_to_bytes(out, &a.x.c1);
  fp_to_bytes(out + 48, &a.x.c0);
  fp_to_bytes(out + 96, &a.y.c1);
  fp_to_bytes(out + 144, &a.y.c0);
}

/* --- public API ---------------------------------------------------- */

/* rc: 1 ok, 2 infinity, 0 invalid */
int blsn_g1_decompress(const uint8_t in[48], uint8_t out[96]) {
  uint8_t flags = in[0];
  if (!(flags & 0x80)) return 0; /* must be compressed */
  int infinity = (flags >> 6) & 1;
  int sign = (flags >> 5) & 1;
  uint8_t xb[48];
  memcpy(xb, in, 48);
  xb[0] &= 0x1f;
  if (infinity) {
    if (sign || !is_zero_bytes(xb, 48)) return 0;
    memset(out, 0, 96);
    return 2;
  }
  fp_t x, y2, y;
  if (!fp_from_bytes(&x, xb)) return 0;
  fp_sqr(&y2, &x);
  fp_mul(&y2, &y2, &x);
  fp_add(&y2, &y2, &FP_B_M);
  if (!fp_sqrt(&y, &y2)) return 0;
  /* pick lexicographically-larger y iff sign bit set */
  fp_t neg_y, y_plain, ny_plain;
  fp_neg(&neg_y, &y);
  fp_from_mont(&y_plain, &y);
  fp_from_mont(&ny_plain, &neg_y);
  int y_larger = 0;
  for (int i = 5; i >= 0; i--) {
    if (y_plain.l[i] > ny_plain.l[i]) { y_larger = 1; break; }
    if (y_plain.l[i] < ny_plain.l[i]) { y_larger = 0; break; }
  }
  if (y_larger != sign) y = neg_y;
  g1_t p;
  g1_set_affine(&p, &x, &y);
  if (!g1_in_subgroup(&p)) return 0;
  fp_to_bytes(out, &x);
  fp_to_bytes(out + 48, &y);
  return 1;
}

int blsn_g2_decompress(const uint8_t in[96], uint8_t out[192]) {
  uint8_t flags = in[0];
  if (!(flags & 0x80)) return 0;
  int infinity = (flags >> 6) & 1;
  int sign = (flags >> 5) & 1;
  uint8_t xb[96];
  memcpy(xb, in, 96);
  xb[0] &= 0x1f;
  if (infinity) {
    if (sign || !is_zero_bytes(xb, 96)) return 0;
    memset(out, 0, 192);
    return 2;
  }
  fp2_t x, y2, y;
  if (!fp_from_bytes(&x.c1, xb) || !fp_from_bytes(&x.c0, xb + 48)) return 0;
  fp2_t b;
  b.c0 = FP_B_M;
  b.c1 = FP_B_M;
  fp2_sqr(&y2, &x);
  fp2_mul(&y2, &y2, &x);
  fp2_add(&y2, &y2, &b);
  if (!fp2_sqrt(&y, &y2)) return 0;
  /* sign: lexicographic on (c1, c0) plain values */
  fp2_t neg_y;
  fp2_neg(&neg_y, &y);
  fp_t yc1, nyc1, yc0, nyc0;
  fp_from_mont(&yc1, &y.c1);
  fp_from_mont(&nyc1, &neg_y.c1);
  fp_from_mont(&yc0, &y.c0);
  fp_from_mont(&nyc0, &neg_y.c0);
  int y_larger = 0, decided = 0;
  for (int i = 5; i >= 0 && !decided; i--) {
    if (yc1.l[i] != nyc1.l[i]) {
      y_larger = yc1.l[i] > nyc1.l[i];
      decided = 1;
    }
  }
  for (int i = 5; i >= 0 && !decided; i--) {
    if (yc0.l[i] != nyc0.l[i]) {
      y_larger = yc0.l[i] > nyc0.l[i];
      decided = 1;
    }
  }
  if (y_larger != sign) y = neg_y;
  g2_t p;
  g2_set_affine(&p, &x, &y);
  if (!g2_in_subgroup(&p)) return 0;
  g2_to_affine_bytes(out, &p);
  return 1;
}

void blsn_g1_compress(const uint8_t aff[96], uint8_t out[48]) {
  if (is_zero_bytes(aff, 96)) {
    memset(out, 0, 48);
    out[0] = 0xc0;
    return;
  }
  memcpy(out, aff, 48);
  out[0] |= 0x80;
  /* sign of y */
  fp_t y, ny, yp, nyp;
  fp_from_bytes(&y, aff + 48);
  fp_neg(&ny, &y);
  fp_from_mont(&yp, &y);
  fp_from_mont(&nyp, &ny);
  for (int i = 5; i >= 0; i--) {
    if (yp.l[i] > nyp.l[i]) {
      out[0] |= 0x20;
      break;
    }
    if (yp.l[i] < nyp.l[i]) break;
  }
}

int blsn_g1_subgroup_check(const uint8_t aff[96]) {
  g1_t p;
  if (!g1_from_affine_bytes(&p, aff)) return 0;
  if (p.inf) return 1;
  return g1_in_subgroup(&p);
}

int blsn_g2_subgroup_check(const uint8_t aff[192]) {
  g2_t p;
  if (!g2_from_affine_bytes(&p, aff)) return 0;
  if (p.inf) return 1;
  return g2_in_subgroup(&p);
}

void blsn_hash_to_g2(const uint8_t *msg, uint32_t msg_len,
                     const uint8_t *dst, uint32_t dst_len,
                     uint8_t out[192]) {
  g2_t p;
  hash_to_g2_point(&p, msg, msg_len, dst, dst_len);
  g2_to_affine_bytes(out, &p);
}

/* Pippenger bucket MSM: out = sum_i scalars[i] * pts[i].
 * pts_aff: n*96B affine (all-zero = infinity), scalars_be: n*32B
 * big-endian. rc: 1 ok, 0 invalid point. The KZG blob path commits
 * 4096-term polynomials; schoolbook per-point ladders would be ~256x
 * slower. Window width follows the usual log(n) rule. */
int blsn_g1_msm(const uint8_t *pts_aff, const uint8_t *scalars_be,
                size_t n, uint8_t out[96]) {
  if (n == 0) {
    memset(out, 0, 96);
    return 1;
  }
  g1_t *ps = (g1_t *)malloc(n * sizeof(g1_t));
  if (!ps) return 0;
  for (size_t i = 0; i < n; i++) {
    if (!g1_from_affine_bytes(&ps[i], pts_aff + i * 96)) {
      free(ps);
      return 0;
    }
  }
  int c = n < 8 ? 3 : n < 64 ? 5 : n < 1024 ? 7 : 9;
  size_t nbuckets = ((size_t)1 << c) - 1;
  g1_t *buckets = (g1_t *)malloc(nbuckets * sizeof(g1_t));
  if (!buckets) {
    free(ps);
    return 0;
  }
  g1_t acc;
  acc.inf = 1;
  int nwin = (256 + c - 1) / c;
  for (int w = nwin - 1; w >= 0; w--) {
    if (!acc.inf)
      for (int k = 0; k < c; k++) g1_dbl(&acc, &acc);
    for (size_t b = 0; b < nbuckets; b++) buckets[b].inf = 1;
    int lo = w * c;
    for (size_t i = 0; i < n; i++) {
      /* c-bit digit at bit offset lo (LSB order) of big-endian scalar */
      uint32_t d = 0;
      for (int b = c - 1; b >= 0; b--) {
        int bit = lo + b;
        if (bit < 256) {
          const uint8_t *s = scalars_be + i * 32;
          d = (d << 1) | ((s[31 - bit / 8] >> (bit % 8)) & 1);
        } else {
          d <<= 1;
        }
      }
      if (d) g1_add(&buckets[d - 1], &buckets[d - 1], &ps[i]);
    }
    /* sum_d d*bucket[d] by suffix running sums */
    g1_t run, sum;
    run.inf = 1;
    sum.inf = 1;
    for (size_t d = nbuckets; d-- > 0;) {
      g1_add(&run, &run, &buckets[d]);
      g1_add(&sum, &sum, &run);
    }
    g1_add(&acc, &acc, &sum);
  }
  free(buckets);
  free(ps);
  g1_to_affine_bytes(out, &acc);
  return 1;
}

void blsn_g1_mul(const uint8_t aff[96], const uint8_t scalar_be[32],
                 uint8_t out[96]) {
  g1_t p, r;
  if (!g1_from_affine_bytes(&p, aff)) {
    memset(out, 0, 96);
    return;
  }
  g1_mul_be(&r, &p, scalar_be, 32);
  g1_to_affine_bytes(out, &r);
}

void blsn_g2_mul(const uint8_t aff[192], const uint8_t scalar_be[32],
                 uint8_t out[192]) {
  g2_t p, r;
  if (!g2_from_affine_bytes(&p, aff)) {
    memset(out, 0, 192);
    return;
  }
  g2_mul_be(&r, &p, scalar_be, 32);
  g2_to_affine_bytes(out, &r);
}

/* rc: 1 ok, 0 invalid input (out untouched) */
int blsn_g1_add(const uint8_t a[96], const uint8_t b[96], uint8_t out[96]) {
  g1_t pa, pb, r;
  if (!g1_from_affine_bytes(&pa, a)) return 0;
  if (!g1_from_affine_bytes(&pb, b)) return 0;
  g1_add(&r, &pa, &pb);
  g1_to_affine_bytes(out, &r);
  return 1;
}

int blsn_g2_add(const uint8_t a[192], const uint8_t b[192],
                uint8_t out[192]) {
  g2_t pa, pb, r;
  if (!g2_from_affine_bytes(&pa, a)) return 0;
  if (!g2_from_affine_bytes(&pb, b)) return 0;
  g2_add(&r, &pa, &pb);
  g2_to_affine_bytes(out, &r);
  return 1;
}

void blsn_g1_generator(uint8_t out[96]) {
  fp_to_bytes(out, &G1_GEN_X);
  fp_to_bytes(out + 48, &G1_GEN_Y);
}

void blsn_g2_generator(uint8_t out[192]) {
  fp_to_bytes(out, &G2_GEN_X.c1);
  fp_to_bytes(out + 48, &G2_GEN_X.c0);
  fp_to_bytes(out + 96, &G2_GEN_Y.c1);
  fp_to_bytes(out + 144, &G2_GEN_Y.c0);
}

/* prod e(P_i, Q_i) == 1; points affine bytes, infinity pairs skipped.
   rc: 1 yes, 0 no, -1 invalid input */
int blsn_pairing_product_is_one(const uint8_t *g1s, const uint8_t *g2s,
                                uint32_t n) {
  fp12_t f;
  fp12_one(&f);
  for (uint32_t i = 0; i < n; i++) {
    g1_t p;
    g2_t q;
    if (!g1_from_affine_bytes(&p, g1s + 96 * i)) return -1;
    if (!g2_from_affine_bytes(&q, g2s + 192 * i)) return -1;
    if (p.inf || q.inf) continue;
    miller_loop_acc(&f, &p.x, &p.y, &q.x, &q.y);
  }
  fp12_t e;
  final_exponentiation(&e, &f);
  return fp12_is_one(&e);
}

/* pairing value raw export for differential tests: e(P,Q) pre-final-exp
   as 12 Fp values (48B BE each, basis c0.c0.c0, c0.c0.c1, c0.c1.c0 ...) */
int blsn_miller_loop(const uint8_t g1[96], const uint8_t g2[192],
                     uint8_t out[576]) {
  g1_t p;
  g2_t q;
  if (!g1_from_affine_bytes(&p, g1)) return -1;
  if (!g2_from_affine_bytes(&q, g2)) return -1;
  fp12_t f;
  fp12_one(&f);
  if (!p.inf && !q.inf) miller_loop_acc(&f, &p.x, &p.y, &q.x, &q.y);
  const fp2_t *cs[6] = {&f.c0.c0, &f.c0.c1, &f.c0.c2,
                        &f.c1.c0, &f.c1.c1, &f.c1.c2};
  for (int i = 0; i < 6; i++) {
    fp_to_bytes(out + 96 * i, &cs[i]->c0);
    fp_to_bytes(out + 96 * i + 48, &cs[i]->c1);
  }
  return 0;
}

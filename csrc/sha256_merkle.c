/* Batched SHA-256 merkleization for SSZ hash_tree_root.
 *
 * Reference analog: @chainsafe/as-sha256 (WASM SIMD batch hasher under
 * persistent-merkle-tree, SURVEY.md §2.1 L0). This is the native
 * hot-loop behind lodestar_tpu.ssz merkleization: hash whole tree
 * levels of 64-byte nodes per call instead of one Python hashlib call
 * per node. Runtime-dispatches to x86 SHA-NI when available, portable
 * C otherwise. Built by lodestar_tpu/crypto/sha256_batch.py (ctypes).
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                               0xa54ff53a, 0x510e527f, 0x9b05688c,
                               0x1f83d9ab, 0x5be0cd19};

/* Padding block for a fixed 64-byte message: 0x80, zeros, bitlen 512 */
static const uint8_t PAD64[64] = {
    0x80, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    0,    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0x02, 0x00};

/* ------------------------------------------------------------------ */
/* Portable scalar compression                                         */
/* ------------------------------------------------------------------ */

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void compress_scalar(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((uint32_t)block[i * 4] << 24) | ((uint32_t)block[i * 4 + 1] << 16) |
           ((uint32_t)block[i * 4 + 2] << 8) | block[i * 4 + 3];
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = ROR(w[i - 15], 7) ^ ROR(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = ROR(w[i - 2], 17) ^ ROR(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

/* ------------------------------------------------------------------ */
/* x86 SHA-NI compression                                              */
/* ------------------------------------------------------------------ */

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>

__attribute__((target("sha,sse4.1,ssse3"))) static void
compress_shani(uint32_t state[8], const uint8_t block[64]) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i TMP = _mm_loadu_si128((const __m128i *)&state[0]);
  __m128i STATE1 = _mm_loadu_si128((const __m128i *)&state[4]);
  TMP = _mm_shuffle_epi32(TMP, 0xB1);          /* CDAB */
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);    /* EFGH */
  __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);    /* ABEF */
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);         /* CDGH */

  const __m128i ABEF_SAVE = STATE0;
  const __m128i CDGH_SAVE = STATE1;

  __m128i MSGV[4];
  for (int i = 0; i < 4; i++)
    MSGV[i] = _mm_shuffle_epi8(
        _mm_loadu_si128((const __m128i *)(block + 16 * i)), MASK);

  for (int i = 0; i < 16; i++) {
    __m128i msg;
    if (i < 4) {
      msg = MSGV[i];
    } else {
      __m128i t = _mm_alignr_epi8(MSGV[(i + 3) & 3], MSGV[(i + 2) & 3], 4);
      __m128i m = _mm_sha256msg1_epu32(MSGV[i & 3], MSGV[(i + 1) & 3]);
      m = _mm_add_epi32(m, t);
      m = _mm_sha256msg2_epu32(m, MSGV[(i + 3) & 3]);
      MSGV[i & 3] = m;
      msg = m;
    }
    __m128i kw = _mm_add_epi32(msg, _mm_loadu_si128((const __m128i *)&K[i * 4]));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, kw);
    kw = _mm_shuffle_epi32(kw, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, kw);
  }

  STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
  STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
  TMP = _mm_shuffle_epi32(STATE0, 0x1B);       /* FEBA */
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);    /* DCHG */
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0); /* DCBA */
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);    /* HGFE */
  _mm_storeu_si128((__m128i *)&state[0], STATE0);
  _mm_storeu_si128((__m128i *)&state[4], STATE1);
}

static int has_shani(void) {
  __builtin_cpu_init();
  return __builtin_cpu_supports("sha") && __builtin_cpu_supports("sse4.1") &&
         __builtin_cpu_supports("ssse3");
}
#else
static int has_shani(void) { return 0; }
static void compress_shani(uint32_t state[8], const uint8_t block[64]) {
  compress_scalar(state, block);
}
#endif

typedef void (*compress_fn)(uint32_t[8], const uint8_t *);
static compress_fn COMPRESS = 0;

static compress_fn get_compress(void) {
  if (!COMPRESS)
    COMPRESS = has_shani() ? compress_shani : compress_scalar;
  return COMPRESS;
}

static void hash64(const uint8_t in[64], uint8_t out[32]) {
  compress_fn f = get_compress();
  uint32_t st[8];
  memcpy(st, H0, sizeof(st));
  f(st, in);
  f(st, PAD64);
  for (int i = 0; i < 8; i++) {
    out[i * 4] = (uint8_t)(st[i] >> 24);
    out[i * 4 + 1] = (uint8_t)(st[i] >> 16);
    out[i * 4 + 2] = (uint8_t)(st[i] >> 8);
    out[i * 4 + 3] = (uint8_t)st[i];
  }
}

/* ------------------------------------------------------------------ */
/* Public API (ctypes)                                                 */
/* ------------------------------------------------------------------ */

/* n independent 64-byte inputs -> n 32-byte digests. */
void hash64_batch(const uint8_t *in, uint8_t *out, size_t n) {
  for (size_t i = 0; i < n; i++)
    hash64(in + i * 64, out + i * 32);
}

/* n independent fixed-length messages (len <= 55: one padded block
 * each) -> n 32-byte digests. Drives the swap-or-not shuffle's
 * per-round decision hashes (seed||round||block, 37 bytes) without a
 * Python-loop hashlib call per 256-index block. */
void hash_small_batch(const uint8_t *in, size_t len, uint8_t *out,
                      size_t n) {
  if (len > 55)
    return; /* caller contract: single-block messages only */
  compress_fn f = get_compress();
  uint64_t bits = (uint64_t)len * 8;
  for (size_t i = 0; i < n; i++) {
    uint8_t block[64];
    memset(block, 0, 64);
    memcpy(block, in + i * len, len);
    block[len] = 0x80;
    block[56] = (uint8_t)(bits >> 56);
    block[57] = (uint8_t)(bits >> 48);
    block[58] = (uint8_t)(bits >> 40);
    block[59] = (uint8_t)(bits >> 32);
    block[60] = (uint8_t)(bits >> 24);
    block[61] = (uint8_t)(bits >> 16);
    block[62] = (uint8_t)(bits >> 8);
    block[63] = (uint8_t)bits;
    uint32_t st[8];
    memcpy(st, H0, sizeof(st));
    f(st, block);
    uint8_t *o = out + i * 32;
    for (int j = 0; j < 8; j++) {
      o[j * 4] = (uint8_t)(st[j] >> 24);
      o[j * 4 + 1] = (uint8_t)(st[j] >> 16);
      o[j * 4 + 2] = (uint8_t)(st[j] >> 8);
      o[j * 4 + 3] = (uint8_t)st[j];
    }
  }
}

/* Full sub-tree merkleization: `count` 32-byte chunks, `depth` levels,
 * virtual zero-subtree padding via zero_hashes (33*32 bytes,
 * zero_hashes[i] = root of depth-i zero subtree). scratch needs
 * (count+1)*32 bytes. Writes the 32-byte root to out. */
void merkle_root(const uint8_t *chunks, size_t count, size_t depth,
                 const uint8_t *zero_hashes, uint8_t *scratch, uint8_t *out) {
  if (count == 0) {
    memcpy(out, zero_hashes + depth * 32, 32);
    return;
  }
  memcpy(scratch, chunks, count * 32);
  size_t n = count;
  for (size_t level = 0; level < depth; level++) {
    if (n == 1) {
      /* lone node: hash with the zero subtree of this level */
      memcpy(scratch + 32, zero_hashes + level * 32, 32);
      hash64(scratch, scratch);
      continue;
    }
    if (n & 1) {
      memcpy(scratch + n * 32, zero_hashes + level * 32, 32);
      n++;
    }
    for (size_t i = 0; i < n / 2; i++)
      hash64(scratch + i * 64, scratch + i * 32);
    n /= 2;
  }
  memcpy(out, scratch, 32);
}

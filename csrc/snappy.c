/* Snappy block-format codec + CRC32C, for ssz_snappy wire framing.
 *
 * Reference analog: the `snappyjs` dependency and in-repo snappy frame
 * codec Lodestar uses for gossip payloads and reqresp `ssz_snappy`
 * encoding (packages/reqresp/src/encodingStrategies/sszSnappy/,
 * network/gossip/encoding.ts:69). Implemented natively (C) like the
 * rest of this repo's host-side hot codecs; exposed through ctypes
 * (lodestar_tpu/utils/snappy.py) which adds the stream framing.
 *
 * Format per google/snappy format_description.txt:
 *   preamble: uncompressed length, little-endian varint
 *   tags: 2 LSBs: 00 literal, 01 copy1 (3-bit len, 11-bit offset),
 *         10 copy2 (6-bit len, 16-bit LE offset), 11 copy4.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#define TAG_LITERAL 0
#define TAG_COPY1 1
#define TAG_COPY2 2
#define TAG_COPY4 3

uint64_t snappy_max_compressed_length(uint64_t n) {
  /* worst case: all literals with 5-byte headers every 2^32 chunk +
   * varint preamble; the canonical bound from the reference impl */
  return 32 + n + n / 6;
}

static int put_varint(uint8_t *dst, uint64_t cap, uint64_t v,
                      uint64_t *off) {
  while (v >= 0x80) {
    if (*off >= cap) return -1;
    dst[(*off)++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  if (*off >= cap) return -1;
  dst[(*off)++] = (uint8_t)v;
  return 0;
}

static int get_varint(const uint8_t *src, uint64_t n, uint64_t *off,
                      uint64_t *out) {
  uint64_t v = 0;
  int shift = 0;
  while (*off < n && shift < 64) {
    uint8_t b = src[(*off)++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return 0;
    }
    shift += 7;
  }
  return -1;
}

static void emit_literal(const uint8_t *src, uint64_t len, uint8_t *dst,
                         uint64_t *off) {
  if (len == 0) return;
  uint64_t n = len - 1;
  if (n < 60) {
    dst[(*off)++] = (uint8_t)(n << 2) | TAG_LITERAL;
  } else if (n < (1u << 8)) {
    dst[(*off)++] = (60u << 2) | TAG_LITERAL;
    dst[(*off)++] = (uint8_t)n;
  } else if (n < (1u << 16)) {
    dst[(*off)++] = (61u << 2) | TAG_LITERAL;
    dst[(*off)++] = (uint8_t)n;
    dst[(*off)++] = (uint8_t)(n >> 8);
  } else if (n < (1ull << 24)) {
    dst[(*off)++] = (62u << 2) | TAG_LITERAL;
    dst[(*off)++] = (uint8_t)n;
    dst[(*off)++] = (uint8_t)(n >> 8);
    dst[(*off)++] = (uint8_t)(n >> 16);
  } else {
    dst[(*off)++] = (63u << 2) | TAG_LITERAL;
    dst[(*off)++] = (uint8_t)n;
    dst[(*off)++] = (uint8_t)(n >> 8);
    dst[(*off)++] = (uint8_t)(n >> 16);
    dst[(*off)++] = (uint8_t)(n >> 24);
  }
  memcpy(dst + *off, src, len);
  *off += len;
}

static void emit_copy(uint64_t offset, uint64_t len, uint8_t *dst,
                      uint64_t *off) {
  /* split long matches into <=64-byte copies */
  while (len >= 68) {
    dst[(*off)++] = (63u << 2) | TAG_COPY2;
    dst[(*off)++] = (uint8_t)offset;
    dst[(*off)++] = (uint8_t)(offset >> 8);
    len -= 64;
  }
  if (len > 64) {
    /* emit 60 so the remainder is >= 4 (min copy len) */
    dst[(*off)++] = (59u << 2) | TAG_COPY2;
    dst[(*off)++] = (uint8_t)offset;
    dst[(*off)++] = (uint8_t)(offset >> 8);
    len -= 60;
  }
  if (len >= 12 || offset >= 2048) {
    dst[(*off)++] = (uint8_t)((len - 1) << 2) | TAG_COPY2;
    dst[(*off)++] = (uint8_t)offset;
    dst[(*off)++] = (uint8_t)(offset >> 8);
  } else {
    dst[(*off)++] = (uint8_t)(((offset >> 8) << 5) | ((len - 4) << 2) |
                              TAG_COPY1);
    dst[(*off)++] = (uint8_t)offset;
  }
}

#define HASH_BITS 14
#define HASH_SIZE (1u << HASH_BITS)

static inline uint32_t load32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static inline uint32_t hash32(uint32_t v) {
  return (v * 0x1e35a7bd) >> (32 - HASH_BITS);
}

/* returns 0 ok; *dst_len in = capacity, out = bytes written */
int snappy_compress(const uint8_t *src, uint64_t n, uint8_t *dst,
                    uint64_t *dst_len) {
  uint64_t cap = *dst_len;
  uint64_t off = 0;
  if (put_varint(dst, cap, n, &off)) return -1;
  if (cap < snappy_max_compressed_length(n)) return -1;

  uint32_t table[HASH_SIZE];
  memset(table, 0xff, sizeof(table));

  uint64_t ip = 0, lit_start = 0;
  if (n >= 15) {
    uint64_t limit = n - 14; /* need 4-byte loads with slack */
    while (ip < limit) {
      uint32_t cur = load32(src + ip);
      uint32_t h = hash32(cur);
      uint32_t cand = table[h];
      table[h] = (uint32_t)ip;
      if (cand != 0xffffffffu && (uint64_t)cand < ip &&
          ip - cand < 65536 && load32(src + cand) == cur) {
        emit_literal(src + lit_start, ip - lit_start, dst, &off);
        /* extend match */
        uint64_t m = 4;
        while (ip + m < n && src[cand + m] == src[ip + m]) m++;
        emit_copy(ip - cand, m, dst, &off);
        ip += m;
        lit_start = ip;
      } else {
        ip++;
      }
    }
  }
  emit_literal(src + lit_start, n - lit_start, dst, &off);
  *dst_len = off;
  return 0;
}

int snappy_uncompressed_length(const uint8_t *src, uint64_t n,
                               uint64_t *out) {
  uint64_t off = 0;
  return get_varint(src, n, &off, out);
}

/* returns 0 ok; *dst_len in = capacity, out = bytes written */
int snappy_uncompress(const uint8_t *src, uint64_t n, uint8_t *dst,
                      uint64_t *dst_len) {
  uint64_t off = 0, total, op = 0, cap = *dst_len;
  if (get_varint(src, n, &off, &total)) return -1;
  if (total > cap) return -1;
  while (off < n) {
    uint8_t tag = src[off++];
    uint64_t len, offset;
    switch (tag & 3) {
      case TAG_LITERAL: {
        len = tag >> 2;
        if (len >= 60) {
          uint32_t extra = (uint32_t)len - 59;
          if (off + extra > n) return -1;
          len = 0;
          for (uint32_t i = 0; i < extra; i++)
            len |= (uint64_t)src[off + i] << (8 * i);
          off += extra;
        }
        len += 1;
        if (off + len > n || op + len > total) return -1;
        memcpy(dst + op, src + off, len);
        off += len;
        op += len;
        break;
      }
      case TAG_COPY1: {
        if (off >= n) return -1;
        len = ((tag >> 2) & 7) + 4;
        offset = ((uint64_t)(tag >> 5) << 8) | src[off++];
        goto do_copy;
      }
      case TAG_COPY2: {
        if (off + 2 > n) return -1;
        len = (tag >> 2) + 1;
        offset = (uint64_t)src[off] | ((uint64_t)src[off + 1] << 8);
        off += 2;
        goto do_copy;
      }
      default: { /* TAG_COPY4 */
        if (off + 4 > n) return -1;
        len = (tag >> 2) + 1;
        offset = (uint64_t)src[off] | ((uint64_t)src[off + 1] << 8) |
                 ((uint64_t)src[off + 2] << 16) |
                 ((uint64_t)src[off + 3] << 24);
        off += 4;
        goto do_copy;
      }
      do_copy : {
        if (offset == 0 || offset > op || op + len > total) return -1;
        /* byte-wise: copies may overlap forward (RLE) */
        for (uint64_t i = 0; i < len; i++) dst[op + i] = dst[op + i - offset];
        op += len;
        break;
      }
    }
  }
  if (op != total) return -1;
  *dst_len = op;
  return 0;
}

/* ---- CRC32C (Castagnoli), table-driven; framing checksums ---- */

static uint32_t crc_table[256];
static int crc_init_done = 0;

static void crc_init(void) {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
    crc_table[i] = c;
  }
  crc_init_done = 1;
}

uint32_t snappy_crc32c(const uint8_t *buf, uint64_t n) {
  if (!crc_init_done) crc_init();
  uint32_t c = 0xffffffffu;
  for (uint64_t i = 0; i < n; i++)
    c = crc_table[(c ^ buf[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

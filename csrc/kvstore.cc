// Embedded ordered KV store: the native persistence engine.
//
// Reference analog: classic-level / LevelDB under @lodestar/db
// (SURVEY.md §2.1 L0, db/src/controller/level.ts:28). Design: an
// in-memory ordered map (the working set of a beacon node's hot
// buckets fits comfortably in RAM) + append-only WAL for durability +
// snapshot compaction. Supports the operations the repository layer
// needs: put/get/delete, batched writes, and ordered range scans in
// both directions (block-archive-by-slot iteration).
//
// C ABI for ctypes (lodestar_tpu/db/native.py). All returned buffers
// are malloc'd copies — free with kv_free.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Store {
  std::map<std::string, std::string> table;
  std::string dir;
  FILE *wal = nullptr;
  std::mutex mu;
  uint64_t wal_records = 0;
};

constexpr uint8_t OP_PUT = 1;
constexpr uint8_t OP_DEL = 2;

std::string wal_path(const std::string &dir) { return dir + "/wal.log"; }
std::string snap_path(const std::string &dir) { return dir + "/snapshot.db"; }

bool read_exact(FILE *f, void *buf, size_t n) {
  return fread(buf, 1, n, f) == n;
}

// record: [op u8][klen u32][vlen u32][key][val]  (vlen=0 for DEL)
bool read_record(FILE *f, uint8_t &op, std::string &k, std::string &v) {
  uint8_t o;
  uint32_t kl, vl;
  if (!read_exact(f, &o, 1)) return false;
  if (!read_exact(f, &kl, 4) || !read_exact(f, &vl, 4)) return false;
  if (kl > (1u << 30) || vl > (1u << 30)) return false;
  k.resize(kl);
  v.resize(vl);
  if (kl && !read_exact(f, &k[0], kl)) return false;
  if (vl && !read_exact(f, &v[0], vl)) return false;
  op = o;
  return true;
}

void write_record(FILE *f, uint8_t op, const char *k, uint32_t kl,
                  const char *v, uint32_t vl) {
  fwrite(&op, 1, 1, f);
  fwrite(&kl, 4, 1, f);
  fwrite(&vl, 4, 1, f);
  if (kl) fwrite(k, 1, kl, f);
  if (vl) fwrite(v, 1, vl, f);
}

void load_file(Store *s, const std::string &path) {
  FILE *f = fopen(path.c_str(), "rb");
  if (!f) return;
  uint8_t op;
  std::string k, v;
  while (read_record(f, op, k, v)) {
    if (op == OP_PUT)
      s->table[k] = v;
    else
      s->table.erase(k);
  }
  fclose(f);
}

}  // namespace

extern "C" {

void *kv_open(const char *dir) {
  auto *s = new Store();
  s->dir = dir;
  load_file(s, snap_path(s->dir));
  load_file(s, wal_path(s->dir));
  s->wal = fopen(wal_path(s->dir).c_str(), "ab");
  if (!s->wal) {
    delete s;
    return nullptr;
  }
  return s;
}

int kv_put(void *h, const char *k, uint32_t kl, const char *v, uint32_t vl) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  s->table[std::string(k, kl)] = std::string(v, vl);
  write_record(s->wal, OP_PUT, k, kl, v, vl);
  s->wal_records++;
  return 0;
}

int kv_delete(void *h, const char *k, uint32_t kl) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  s->table.erase(std::string(k, kl));
  write_record(s->wal, OP_DEL, k, kl, nullptr, 0);
  s->wal_records++;
  return 0;
}

// Batch: packed records [op u8][klen u32][vlen u32][key][val]*
int kv_batch(void *h, const char *buf, uint64_t len) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  uint64_t off = 0;
  while (off < len) {
    if (off + 9 > len) return -1;
    uint8_t op = (uint8_t)buf[off];
    uint32_t kl, vl;
    memcpy(&kl, buf + off + 1, 4);
    memcpy(&vl, buf + off + 5, 4);
    off += 9;
    if (off + kl + vl > len) return -1;
    std::string k(buf + off, kl);
    off += kl;
    std::string v(buf + off, vl);
    off += vl;
    if (op == OP_PUT)
      s->table[k] = v;
    else
      s->table.erase(k);
    write_record(s->wal, op, k.data(), kl, v.data(), vl);
    s->wal_records++;
  }
  return 0;
}

// Returns malloc'd value copy (caller kv_free) or NULL. *vl = length.
char *kv_get(void *h, const char *k, uint32_t kl, uint32_t *vl) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->table.find(std::string(k, kl));
  if (it == s->table.end()) return nullptr;
  *vl = (uint32_t)it->second.size();
  char *out = (char *)malloc(it->second.size() ? it->second.size() : 1);
  memcpy(out, it->second.data(), it->second.size());
  return out;
}

// Range scan [start, end) (end empty = unbounded), ascending or
// reverse, up to `limit` entries (0 = unlimited). Returns a malloc'd
// packed buffer [klen u32][vlen u32][key][val]* ; *out_len = bytes,
// *out_count = entries.
char *kv_range(void *h, const char *start, uint32_t sl, const char *end,
               uint32_t el, int reverse, uint64_t limit, uint64_t *out_len,
               uint64_t *out_count) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  std::string lo(start, sl), hi(end, el);
  auto it = s->table.lower_bound(lo);
  auto stop = el ? s->table.lower_bound(hi) : s->table.end();
  std::vector<std::pair<const std::string *, const std::string *>> hits;
  for (; it != stop; ++it) hits.emplace_back(&it->first, &it->second);
  if (reverse) {
    std::reverse(hits.begin(), hits.end());
  }
  if (limit && hits.size() > limit) hits.resize(limit);
  uint64_t total = 0;
  for (auto &kv : hits) total += 8 + kv.first->size() + kv.second->size();
  char *buf = (char *)malloc(total ? total : 1);
  uint64_t off = 0;
  for (auto &kv : hits) {
    uint32_t kl2 = (uint32_t)kv.first->size();
    uint32_t vl2 = (uint32_t)kv.second->size();
    memcpy(buf + off, &kl2, 4);
    memcpy(buf + off + 4, &vl2, 4);
    off += 8;
    memcpy(buf + off, kv.first->data(), kl2);
    off += kl2;
    memcpy(buf + off, kv.second->data(), vl2);
    off += vl2;
  }
  *out_len = total;
  *out_count = hits.size();
  return buf;
}

uint64_t kv_count(void *h) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->table.size();
}

int kv_flush(void *h) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  fflush(s->wal);
  return 0;
}

// Write a fresh snapshot and truncate the WAL.
int kv_compact(void *h) {
  auto *s = static_cast<Store *>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  std::string tmp = snap_path(s->dir) + ".tmp";
  FILE *f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  for (auto &kv : s->table)
    write_record(f, OP_PUT, kv.first.data(), (uint32_t)kv.first.size(),
                 kv.second.data(), (uint32_t)kv.second.size());
  fflush(f);
  fclose(f);
  if (rename(tmp.c_str(), snap_path(s->dir).c_str()) != 0) return -1;
  fclose(s->wal);
  s->wal = fopen(wal_path(s->dir).c_str(), "wb");
  s->wal_records = 0;
  return s->wal ? 0 : -1;
}

void kv_close(void *h) {
  auto *s = static_cast<Store *>(h);
  {
    std::lock_guard<std::mutex> lock(s->mu);
    if (s->wal) {
      fflush(s->wal);
      fclose(s->wal);
    }
  }
  delete s;
}

void kv_free(char *p) { free(p); }

}  // extern "C"
